"""Filecoin block headers: the 16-field tuple, decode + fixture builder.

Reference parity: `HeaderLite` (`src/proofs/common/decode.rs:100-118`) decodes
fields 5 (parents), 7 (height), 8 (parent_state_root),
9 (parent_message_receipts), 10 (messages), 12 (timestamp),
14 (fork_signaling) and ignores the rest. The builder emits a full 16-field
tuple so fixture headers round-trip through the same decoder the proof
engines use.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, NamedTuple

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.core.dagcbor import decode as cbor_decode
from ipc_proofs_tpu.core.dagcbor import encode as cbor_encode

__all__ = [
    "BlockHeader",
    "LiteHeader",
    "decode_header_lite",
    "extract_parent_state_root",
]

# memoized native decoder entries (absent = untried, False = unavailable)
_native_memo: dict = {}


def _resolve_native(attr: str):
    """Resolve (once per attr) a native decoder from the dagcbor extension,
    or False when the extension (or that entry) is unavailable."""
    if attr not in _native_memo:
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext

        ext = load_dagcbor_ext()
        _native_memo[attr] = (
            getattr(ext, attr) if ext is not None and hasattr(ext, attr) else False
        )
    return _native_memo[attr]


def _native_decode_header_lite():
    """The C 5-field validated header decoder, or False."""
    return _resolve_native("decode_header_lite")


def _native_decode_header():
    """The C validating-skip header decoder, or False when the extension is
    unavailable — shared by both lite decode paths."""
    return _resolve_native("decode_header")


def _validate_core_fields(fields: list) -> None:
    """Type checks on the fields verification reads — shared by the full
    and lite decoders so their acceptance can never diverge."""
    parents = fields[5]
    if not isinstance(parents, list):
        raise ValueError("header parents must be a CID list")
    for c in parents:
        if not isinstance(c, CID):
            raise ValueError("header parents must be a CID list")
    for idx, name in (
        (8, "parent_state_root"),
        (9, "parent_message_receipts"),
        (10, "messages"),
    ):
        if not isinstance(fields[idx], CID):
            raise ValueError(f"header field {name} must be a CID")


class LiteHeader(NamedTuple):
    """The five header fields verification reads, and nothing else — the
    batch verifier decodes two headers per proof group, and a 17-field
    dataclass construction per decode was its hottest Python line. Shares
    attribute names with :class:`BlockHeader`, so the verifier and the
    batched exec-order walker accept either."""

    parents: "list[CID]"
    height: int
    parent_state_root: CID
    parent_message_receipts: CID
    messages: CID


def decode_header_lite(raw: bytes) -> "LiteHeader":
    """Verification-only header decode with :meth:`BlockHeader.decode`'s
    exact acceptance (the C ``decode_header`` walks the full grammar in
    validating-skip mode — strict UTF-8, map keys, tag-42 CID bytes), but
    returns the 5-field :class:`LiteHeader`. Falls back to the full Python
    decode when the extension is unavailable.

    Fast path: the C ``decode_header_lite`` folds the core-field type
    validation in and returns exactly the 5-tuple (no 16-item list per
    header — the batch verifier decodes two headers per proof group)."""
    lite = _native_decode_header_lite()
    if lite is not False:
        return LiteHeader._make(lite(raw))
    native = _native_decode_header()
    if native is False:
        h = BlockHeader.decode(raw)
        return LiteHeader(
            h.parents, h.height, h.parent_state_root,
            h.parent_message_receipts, h.messages,
        )
    fields = native(raw)
    _validate_core_fields(fields)
    return LiteHeader(fields[5], fields[7], fields[8], fields[9], fields[10])


@dataclass
class BlockHeader:
    """The fields the proof system reads, plus opaque padding for the rest."""

    parents: list[CID]
    height: int
    parent_state_root: CID
    parent_message_receipts: CID
    messages: CID
    timestamp: int = 0
    fork_signaling: int = 0
    miner: Any = None
    parent_weight: bytes = b""
    # Opaque fields kept only so decode(encode(h)) is byte-stable.
    _ticket: Any = None
    _election_proof: Any = None
    _beacon_entries: Any = field(default_factory=list)
    _winpost_proof: Any = field(default_factory=list)
    _bls_aggregate: Any = None
    _block_sig: Any = None
    _parent_base_fee: bytes = b""

    @classmethod
    def decode(cls, raw: bytes) -> "BlockHeader":
        fields = cbor_decode(raw)
        if not (isinstance(fields, list) and len(fields) == 16):
            raise ValueError(f"block header must be a 16-tuple, got {type(fields)}")
        return cls._from_fields(fields)

    @classmethod
    def decode_lite(cls, raw: bytes) -> "BlockHeader":
        """Verification-only decode: identical acceptance to :meth:`decode`
        (the C ``decode_header`` walks the full grammar in validating-skip
        mode, including strict UTF-8, map-key, and tag-42 CID byte checks),
        but the opaque fields (ticket, election proof, beacon entries,
        signatures, …) come back as ``None`` instead of being materialized.
        The returned header must NOT be re-encoded — ``encode()`` would emit
        nulls where the opaque payloads were. Falls back to the full decode
        when the extension is unavailable. Differential acceptance is
        covered by tests/test_state.py."""
        native = _native_decode_header()
        if native is False:
            return cls.decode(raw)
        header = cls._from_fields(native(raw))
        header._lite = True  # encode() raises instead of emitting nulls
        return header

    @classmethod
    def _from_fields(cls, fields: list) -> "BlockHeader":
        _validate_core_fields(fields)
        return cls(
            miner=fields[0],
            _ticket=fields[1],
            _election_proof=fields[2],
            _beacon_entries=fields[3],
            _winpost_proof=fields[4],
            parents=fields[5],
            parent_weight=fields[6],
            height=fields[7],
            parent_state_root=fields[8],
            parent_message_receipts=fields[9],
            messages=fields[10],
            _bls_aggregate=fields[11],
            timestamp=fields[12],
            _block_sig=fields[13],
            fork_signaling=fields[14],
            _parent_base_fee=fields[15],
        )

    # set on decode_lite results: opaque fields were validated but not
    # materialized, so re-encoding would silently emit nulls in their place
    _lite: bool = field(default=False, compare=False, repr=False)

    def encode(self) -> bytes:
        if self._lite:
            raise ValueError(
                "cannot re-encode a decode_lite header: opaque fields were "
                "not materialized (use BlockHeader.decode for round-trips)"
            )
        return cbor_encode(
            [
                self.miner,
                self._ticket,
                self._election_proof,
                self._beacon_entries,
                self._winpost_proof,
                self.parents,
                self.parent_weight,
                self.height,
                self.parent_state_root,
                self.parent_message_receipts,
                self.messages,
                self._bls_aggregate,
                self.timestamp,
                self._block_sig,
                self.fork_signaling,
                self._parent_base_fee,
            ]
        )

    def cid(self) -> CID:
        return CID.hash_of(self.encode())


def extract_parent_state_root(raw: bytes) -> CID:
    """Parent state root straight from raw header CBOR
    (reference `common/decode.rs:121-124`)."""
    return BlockHeader.decode(raw).parent_state_root
