"""Filecoin state schema: addresses, headers, actors, events, storage slots.

Replaces the reference's `fvm_shared` types and its decode helpers
(`src/proofs/common/decode.rs`, `src/proofs/common/evm.rs`,
`src/proofs/storage/decode.rs`, `src/client/types.rs`). Includes *builders*
for every type so synthetic chains can be written for hermetic tests — a
capability the reference lacks entirely.
"""

from ipc_proofs_tpu.state.address import Address, Protocol
from ipc_proofs_tpu.state.header import BlockHeader, extract_parent_state_root
from ipc_proofs_tpu.state.actors import (
    ActorState,
    EvmStateLite,
    StateRoot,
    get_actor_state,
    parse_evm_state,
)
from ipc_proofs_tpu.state.events import (
    ActorEvent,
    EventEntry,
    EvmLog,
    Receipt,
    StampedEvent,
    ascii_to_bytes32,
    extract_evm_log,
    hash_event_signature,
    left_pad_32,
)
from ipc_proofs_tpu.state.storage import (
    calculate_storage_slot,
    compute_mapping_slot,
    read_storage_slot,
)

__all__ = [
    "Address",
    "Protocol",
    "BlockHeader",
    "extract_parent_state_root",
    "StateRoot",
    "ActorState",
    "EvmStateLite",
    "get_actor_state",
    "parse_evm_state",
    "EventEntry",
    "ActorEvent",
    "StampedEvent",
    "Receipt",
    "EvmLog",
    "extract_evm_log",
    "hash_event_signature",
    "ascii_to_bytes32",
    "left_pad_32",
    "read_storage_slot",
    "compute_mapping_slot",
    "calculate_storage_slot",
]
