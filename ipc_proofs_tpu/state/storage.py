"""FEVM contract storage: slot math and the five on-disk slot encodings.

Reference parity: `read_storage_slot` (`src/proofs/storage/decode.rs:36-97`)
tries, in order:

- A1 inline ``[params, [SmallMap]]``
- A2 inline ``[params, SmallMap]``
- A3 bare ``SmallMap`` (= ``{"v": [[k, v], ...]}``)
- B1 wrapper ``[root_cid, bitwidth]`` → HAMT
- B2 wrapper ``{"root": cid, "bitwidth": n}`` → HAMT
- C  direct HAMT at the root CID, protocol bit width 5

and `compute_mapping_slot` (`src/proofs/storage/utils.rs:5-19`) implements
Solidity mapping slot addressing ``keccak(key32 ++ be_pad32(slot_index))``.
"""

from __future__ import annotations

from typing import Optional

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.core.dagcbor import decode as cbor_decode
from ipc_proofs_tpu.core.hashes import keccak256
from ipc_proofs_tpu.ipld.hamt import HAMT, HAMT_BIT_WIDTH
from ipc_proofs_tpu.state.events import ascii_to_bytes32
from ipc_proofs_tpu.store.blockstore import Blockstore

__all__ = [
    "read_storage_slot",
    "classify_storage_root",
    "compute_mapping_slot",
    "calculate_storage_slot",
]


def _small_map_lookup(obj, slot_key: bytes) -> "tuple[bool, Optional[bytes]]":
    """Try to interpret ``obj`` as SmallMap ``{"v": [[k, v], ...]}``.

    Returns (matched_shape, value_or_None).
    """
    if not _small_map_shape(obj):
        return False, None
    for key, value in obj["v"]:
        if key == slot_key:
            return True, value
    return True, None


def read_storage_slot(
    store: Blockstore, contract_state_root: CID, slot_key: bytes
) -> Optional[bytes]:
    """Read a 32-byte FEVM storage slot; ``slot_key`` is the 32-byte preimage
    digest (already keccak'd for mappings). Missing key → None (= zero)."""
    if len(slot_key) != 32:
        raise ValueError("slot key must be 32 bytes")
    raw = store.get(contract_state_root)
    if raw is None:
        raise KeyError(f"missing contract_state root {contract_state_root}")
    obj = cbor_decode(raw)

    # A1) [params, [SmallMap]]
    if (
        isinstance(obj, list)
        and len(obj) == 2
        and isinstance(obj[0], bytes)
        and isinstance(obj[1], list)
        and obj[1]
    ):
        matched, value = _small_map_lookup(obj[1][0], slot_key)
        if matched:
            return value

    # A2) [params, SmallMap]
    if isinstance(obj, list) and len(obj) == 2 and isinstance(obj[0], bytes):
        matched, value = _small_map_lookup(obj[1], slot_key)
        if matched:
            return value

    # A3) bare SmallMap
    matched, value = _small_map_lookup(obj, slot_key)
    if matched:
        return value

    # B1) [root_cid, bitwidth] wrapper
    if (
        isinstance(obj, list)
        and len(obj) == 2
        and isinstance(obj[0], CID)
        and isinstance(obj[1], int)
    ):
        hamt = HAMT.load(store, obj[0], bit_width=obj[1])
        return _slot_bytes(hamt.get(slot_key))

    # B2) {"root": cid, "bitwidth": n} wrapper
    if isinstance(obj, dict) and isinstance(obj.get("root"), CID) and "bitwidth" in obj:
        hamt = HAMT.load(store, obj["root"], bit_width=obj["bitwidth"])
        return _slot_bytes(hamt.get(slot_key))

    # C) direct HAMT at the root, protocol default bit width
    hamt = HAMT.load(store, contract_state_root, bit_width=HAMT_BIT_WIDTH)
    return _slot_bytes(hamt.get(slot_key))


def _slot_bytes(value) -> Optional[bytes]:
    """A slot HAMT's values are byte buffers; the reference's typed HAMT
    deserialize makes any other CBOR type a decode ERROR in the selected
    arm (no further fallback), so reject rather than fall through."""
    if value is not None and not isinstance(value, bytes):
        raise ValueError("storage slot value must be bytes")
    return value


def classify_storage_root(obj) -> "tuple[str, object, int]":
    """Resolve which arm of :func:`read_storage_slot`'s five-encoding
    cascade a DECODED storage-root object takes — the arms are purely
    type-directed (a SmallMap is a ``{"v": [...]}`` dict, so HAMT nodes
    ``[bytes, list]`` can never shape-match an A-case), which lets batch
    drivers resolve the encoding ONCE per root and route the HAMT arms
    through the C batched walker. Returns:

    - ``("smallmap", map_obj, 0)`` — A1/A2/A3: every key resolves against
      ``map_obj`` (value or None), nothing beyond the root is touched;
    - ``("hamt", root_or_cid, bit_width)`` — B1/B2/C: walk a HAMT.
    """
    if (
        isinstance(obj, list)
        and len(obj) == 2
        and isinstance(obj[0], bytes)
        and isinstance(obj[1], list)
        and obj[1]
        and _small_map_shape(obj[1][0])
    ):
        return ("smallmap", obj[1][0], 0)
    if isinstance(obj, list) and len(obj) == 2 and isinstance(obj[0], bytes):
        if _small_map_shape(obj[1]):
            return ("smallmap", obj[1], 0)
    if _small_map_shape(obj):
        return ("smallmap", obj, 0)
    if (
        isinstance(obj, list)
        and len(obj) == 2
        and isinstance(obj[0], CID)
        and isinstance(obj[1], int)
    ):
        return ("hamt", obj[0], obj[1])
    if isinstance(obj, dict) and isinstance(obj.get("root"), CID) and "bitwidth" in obj:
        return ("hamt", obj["root"], obj["bitwidth"])
    return ("hamt", None, HAMT_BIT_WIDTH)  # C: direct HAMT at the root itself


def _small_map_shape(obj) -> bool:
    """SmallMap *shape* check — exactly `_small_map_lookup`'s acceptance,
    key-independent (the cascade's matched/fall-through is type-driven).
    Values must be CBOR bytes: the reference's SmallMap arm deserializes
    values as byte buffers, so a text-valued map fails that arm and the
    cascade falls through (round-5 soak find: a text value classified as
    SmallMap leaked a TypeError out of the hex compare)."""
    if not (isinstance(obj, dict) and set(obj) == {"v"} and isinstance(obj["v"], list)):
        return False
    for pair in obj["v"]:
        if not (
            isinstance(pair, list)
            and len(pair) == 2
            and isinstance(pair[0], bytes)
            and isinstance(pair[1], bytes)
        ):
            return False
    return True


def compute_mapping_slot(key32: bytes, slot_index: int) -> bytes:
    """Solidity mapping slot: ``keccak256(key32 ++ uint256_be(slot_index))``."""
    if len(key32) != 32:
        raise ValueError("mapping key must be 32 bytes")
    return keccak256(key32 + slot_index.to_bytes(32, "big"))


def calculate_storage_slot(subnet_ascii: str, slot_index: int) -> bytes:
    """Mapping slot for an ASCII subnet id (reference `storage/utils.rs:16-19`)."""
    return compute_mapping_slot(ascii_to_bytes32(subnet_ascii), slot_index)
