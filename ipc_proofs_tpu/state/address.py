"""Filecoin addresses: ID / secp / actor / BLS / delegated (f410 EVM).

Replaces `fvm_shared::address` as used by the reference
(`src/proofs/common/address.rs`, `src/proofs/common/decode.rs:34`).

Byte form (the state-tree HAMT key): ``protocol_byte ++ payload`` where
payload is a uvarint actor ID (protocol 0), a raw hash (1/2/3), or
``uvarint(namespace) ++ subaddress`` (protocol 4).

String form: ``f``/``t`` + protocol digit + base32-lower(payload ++ checksum)
with checksum = blake2b-4 over ``protocol_byte ++ payload``; ID addresses use
the decimal id; delegated use ``f4<namespace>f<base32>``.
"""

from __future__ import annotations

import base64
import hashlib
from dataclasses import dataclass
from enum import IntEnum

from ipc_proofs_tpu.core.varint import decode_uvarint, encode_uvarint

__all__ = ["Address", "Protocol", "EAM_NAMESPACE"]

EAM_NAMESPACE = 10  # the Ethereum Address Manager actor: f410 addresses


class Protocol(IntEnum):
    ID = 0
    SECP256K1 = 1
    ACTOR = 2
    BLS = 3
    DELEGATED = 4


_PAYLOAD_SIZES = {Protocol.SECP256K1: 20, Protocol.ACTOR: 20, Protocol.BLS: 48}


def _checksum(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=4).digest()


def _b32(data: bytes) -> str:
    return base64.b32encode(data).decode("ascii").rstrip("=").lower()


def _b32_decode(text: str) -> bytes:
    pad = (-len(text)) % 8
    return base64.b32decode(text.upper() + "=" * pad)


@dataclass(frozen=True)
class Address:
    protocol: Protocol
    payload: bytes  # uvarint(id) for ID; raw hash; uvarint(ns)+sub for delegated

    # --- constructors ------------------------------------------------------

    @classmethod
    def new_id(cls, actor_id: int) -> "Address":
        return cls(Protocol.ID, encode_uvarint(actor_id))

    @classmethod
    def new_delegated(cls, namespace: int, subaddress: bytes) -> "Address":
        return cls(Protocol.DELEGATED, encode_uvarint(namespace) + subaddress)

    @classmethod
    def from_eth_address(cls, eth_addr: "str | bytes") -> "Address":
        """f410 delegated address for a 20-byte EVM address."""
        if isinstance(eth_addr, str):
            eth_addr = bytes.fromhex(eth_addr.removeprefix("0x"))
        if len(eth_addr) != 20:
            raise ValueError(f"EVM address must be 20 bytes, got {len(eth_addr)}")
        return cls.new_delegated(EAM_NAMESPACE, eth_addr)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Address":
        if not raw:
            raise ValueError("empty address bytes")
        protocol = Protocol(raw[0])
        payload = raw[1:]
        cls._validate(protocol, payload)
        return cls(protocol, payload)

    @classmethod
    def from_string(cls, text: str) -> "Address":
        """Parse ``f…``/``t…`` addresses (testnet prefix normalized away,
        like the reference's `parse_address`, `common/address.rs:65-77`)."""
        if len(text) < 2 or text[0] not in "ft":
            raise ValueError(f"invalid address string {text!r}")
        proto_char = text[1]
        body = text[2:]
        if proto_char == "0":
            return cls.new_id(int(body))
        if proto_char in "123":
            protocol = Protocol(int(proto_char))
            decoded = _b32_decode(body)
            payload, check = decoded[:-4], decoded[-4:]
            if _checksum(bytes([protocol]) + payload) != check:
                raise ValueError(f"address checksum mismatch in {text!r}")
            cls._validate(protocol, payload)
            return cls(protocol, payload)
        if proto_char == "4":
            ns_str, sep, sub_str = body.partition("f")
            if not sep:
                raise ValueError(f"malformed delegated address {text!r}")
            namespace = int(ns_str)
            decoded = _b32_decode(sub_str)
            subaddress, check = decoded[:-4], decoded[-4:]
            payload = encode_uvarint(namespace) + subaddress
            if _checksum(bytes([Protocol.DELEGATED]) + payload) != check:
                raise ValueError(f"address checksum mismatch in {text!r}")
            return cls(Protocol.DELEGATED, payload)
        raise ValueError(f"unknown address protocol {proto_char!r}")

    @staticmethod
    def _validate(protocol: Protocol, payload: bytes) -> None:
        expected = _PAYLOAD_SIZES.get(protocol)
        if expected is not None and len(payload) != expected:
            raise ValueError(
                f"protocol {protocol.name} payload must be {expected} bytes, got {len(payload)}"
            )
        if protocol == Protocol.ID:
            decode_uvarint(payload)  # must be a single valid uvarint

    # --- accessors ---------------------------------------------------------

    def id(self) -> int:
        if self.protocol != Protocol.ID:
            raise ValueError(f"not an ID address: {self}")
        value, offset = decode_uvarint(self.payload)
        if offset != len(self.payload):
            raise ValueError("trailing bytes in ID payload")
        return value

    def delegated_parts(self) -> tuple[int, bytes]:
        if self.protocol != Protocol.DELEGATED:
            raise ValueError(f"not a delegated address: {self}")
        namespace, offset = decode_uvarint(self.payload)
        return namespace, self.payload[offset:]

    def to_bytes(self) -> bytes:
        """The state-tree HAMT key form."""
        return bytes([self.protocol]) + self.payload

    def __str__(self) -> str:
        if self.protocol == Protocol.ID:
            return f"f0{self.id()}"
        if self.protocol == Protocol.DELEGATED:
            namespace, sub = self.delegated_parts()
            check = _checksum(self.to_bytes())
            return f"f4{namespace}f{_b32(sub + check)}"
        check = _checksum(self.to_bytes())
        return f"f{int(self.protocol)}{_b32(self.payload + check)}"
