"""Actor events, receipts, and EVM log extraction.

Reference parity:
- `StampedEvent{emitter, event}` / `ActorEvent{entries}` / entry tuples
  ≈ `fvm_shared::event` (used at `events/generator.rs:215-233`).
- `Receipt` ≈ `fvm_shared::receipt::Receipt`, the nv18+ 4-tuple with
  optional `events_root`.
- `extract_evm_log` handles both on-chain encodings
  (`src/proofs/common/evm.rs:13-59`): Case A explicit concatenated
  ``topics``+``data``; Case B compact ``t1..t4``+``d``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import Optional

from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.core.hashes import keccak256

__all__ = [
    "EventEntry",
    "ActorEvent",
    "StampedEvent",
    "Receipt",
    "EvmLog",
    "extract_evm_log",
    "hash_event_signature",
    "ascii_to_bytes32",
    "left_pad_32",
    "IPLD_RAW",
]

IPLD_RAW = 0x55  # codec used for event entry values


@dataclass
class EventEntry:
    """``[flags, key, codec, value]``."""

    flags: int
    key: str
    codec: int
    value: bytes

    @classmethod
    def from_tuple(cls, fields: list) -> "EventEntry":
        if not (isinstance(fields, list) and len(fields) == 4):
            raise ValueError("event entry must be a 4-tuple")
        # fvm_shared's Entry is {flags: u64, key: String, codec: u64,
        # value: RawBytes}: every field's CBOR major must match or serde
        # rejects the block. The native scanner's emit_event enforces the
        # same four (rd_uint flags/codec, major-3 key, rd_bytes value).
        flags, key, codec, value = fields
        if not isinstance(flags, int) or isinstance(flags, bool) or flags < 0:
            raise ValueError("event entry flags must be an unsigned int")
        if not isinstance(key, str):
            raise ValueError("event entry key must be text")
        if not isinstance(codec, int) or isinstance(codec, bool) or codec < 0:
            raise ValueError("event entry codec must be an unsigned int")
        if not isinstance(value, bytes):
            raise ValueError("event entry value must be bytes")
        return cls(flags=flags, key=key, codec=codec, value=value)

    def to_tuple(self) -> list:
        return [self.flags, self.key, self.codec, self.value]


@dataclass
class ActorEvent:
    """Transparent wrapper over the entry list."""

    entries: list[EventEntry] = field(default_factory=list)

    @classmethod
    def from_cbor(cls, value: list) -> "ActorEvent":
        if not isinstance(value, list):
            raise ValueError("ActorEvent entries must be an array")
        return cls(entries=[EventEntry.from_tuple(e) for e in value])

    def to_cbor(self) -> list:
        return [e.to_tuple() for e in self.entries]


@dataclass
class StampedEvent:
    """``[emitter_actor_id, actor_event]``."""

    emitter: int
    event: ActorEvent

    @classmethod
    def from_cbor(cls, value: list) -> "StampedEvent":
        if not (isinstance(value, list) and len(value) == 2):
            raise ValueError("StampedEvent must be a 2-tuple")
        emitter = value[0]
        # ActorID is u64 (CBOR major 0): a text/bytes/negative emitter must
        # reject exactly like the native scanner's rd_uint / serde's u64.
        if not isinstance(emitter, int) or isinstance(emitter, bool) or emitter < 0:
            raise ValueError("StampedEvent emitter must be an unsigned int")
        return cls(emitter=emitter, event=ActorEvent.from_cbor(value[1]))

    def to_cbor(self) -> list:
        return [self.emitter, self.event.to_cbor()]


@dataclass
class Receipt:
    """nv18+ message receipt: ``[exit_code, return_data, gas_used, events_root]``."""

    exit_code: int
    return_data: bytes
    gas_used: int
    events_root: Optional[CID] = None

    @classmethod
    def from_cbor(cls, value: list) -> "Receipt":
        if not isinstance(value, list) or len(value) not in (3, 4):
            raise ValueError("receipt must be a 3/4-tuple")
        events_root = value[3] if len(value) == 4 else None
        if events_root is not None and not isinstance(events_root, CID):
            raise ValueError("receipt events_root must be a CID or null")
        return cls(
            exit_code=value[0],
            return_data=value[1],
            gas_used=value[2],
            events_root=events_root,
        )

    def to_cbor(self) -> list:
        return [self.exit_code, self.return_data, self.gas_used, self.events_root]


@dataclass
class EvmLog:
    topics: list[bytes]  # each exactly 32 bytes
    data: bytes


def extract_evm_log(event: ActorEvent) -> Optional[EvmLog]:
    """Extract an EVM log from an actor event, or None if it isn't EVM-shaped.

    Case A: a ``topics`` entry holding concatenated 32-byte topics plus an
    optional ``data`` entry. Case B: compact ``t1``..``t4`` entries (each 32
    bytes) plus optional ``d``. Mirrors reference `common/evm.rs:13-59`
    exactly, including the rejection rules.
    """
    entries = {e.key: e.value for e in event.entries}

    if "topics" in entries:
        topics_bytes = entries["topics"]
        if len(topics_bytes) % 32 != 0:
            return None
        topics = [topics_bytes[i : i + 32] for i in range(0, len(topics_bytes), 32)]
        return EvmLog(topics=topics, data=entries.get("data", b""))

    topics = []
    for key in ("t1", "t2", "t3", "t4"):
        if key not in entries:
            break
        value = entries[key]
        if len(value) != 32:
            return None
        topics.append(value)
    if not topics:
        return None
    return EvmLog(topics=topics, data=entries.get("d", b""))


@lru_cache(maxsize=4096)
def hash_event_signature(signature: str) -> bytes:
    """keccak256 of the Solidity event signature → topic0 (memoized —
    fixture builders and matchers hash the same few signatures millions of
    times; the scalar keccak is ~40 µs)."""
    return keccak256(signature.encode("utf-8"))


def ascii_to_bytes32(text: str) -> bytes:
    """Right-pad an ASCII string to 32 bytes (subnet-id topics)."""
    raw = text.encode("utf-8")[:32]
    return raw + b"\x00" * (32 - len(raw))


def left_pad_32(value: bytes) -> bytes:
    """Left-pad (or left-truncate) to 32 bytes — EVM storage value form."""
    if len(value) >= 32:
        return value[-32:]
    return b"\x00" * (32 - len(value)) + value
