"""State tree: StateRoot, ActorState, and the EVM actor's state tuple.

Reference parity: `get_actor_state` (`src/proofs/common/decode.rs:17-42`)
walks StateRoot → actors HAMT (bit width 5) → ActorState keyed by the ID
address bytes; `parse_evm_state` (`:79-97`) tries the 6-field layout then
falls back to 5-field. Builders for all three exist here for fixtures.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ipc_proofs_tpu.core.bigint import bigint_from_bytes, bigint_to_bytes
from ipc_proofs_tpu.core.cid import CID
from ipc_proofs_tpu.core.dagcbor import decode as cbor_decode
from ipc_proofs_tpu.ipld.hamt import HAMT, HAMT_BIT_WIDTH
from ipc_proofs_tpu.state.address import Address
from ipc_proofs_tpu.store.blockstore import Blockstore

__all__ = ["StateRoot", "ActorState", "EvmStateLite", "get_actor_state", "parse_evm_state"]


@dataclass
class StateRoot:
    """v5 state-root wrapper: ``[version, actors_root, info]``."""

    version: int
    actors: CID
    info: CID

    @classmethod
    def decode(cls, raw: bytes) -> "StateRoot":
        fields = cbor_decode(raw)
        if not (isinstance(fields, list) and len(fields) == 3 and isinstance(fields[1], CID)):
            raise ValueError("malformed StateRoot")
        return cls(version=fields[0], actors=fields[1], info=fields[2])

    def to_tuple(self) -> list:
        return [self.version, self.actors, self.info]


@dataclass
class ActorState:
    """``[code, head(state), call_seq_num, balance, delegated_address?]``.

    Decode tolerates both the 4-field (pre-v10) and 5-field layouts, like
    `fvm_shared::state::ActorState`.
    """

    code: CID
    state: CID
    call_seq_num: int
    balance: int
    delegated_address: Optional[bytes] = None  # raw address bytes or None

    @classmethod
    def from_tuple(cls, fields: list) -> "ActorState":
        if not isinstance(fields, list) or len(fields) not in (4, 5):
            raise ValueError(f"ActorState must be a 4/5-tuple, got {fields!r}")
        delegated = fields[4] if len(fields) == 5 else None
        return cls(
            code=fields[0],
            state=fields[1],
            call_seq_num=fields[2],
            balance=bigint_from_bytes(fields[3]),
            delegated_address=delegated,
        )

    def to_tuple(self) -> list:
        return [
            self.code,
            self.state,
            self.call_seq_num,
            bigint_to_bytes(self.balance),
            self.delegated_address,
        ]


def get_actor_state(store: Blockstore, state_root_cid: CID, address: Address) -> ActorState:
    """StateRoot → actors HAMT → ActorState for an ID address.

    Every block touched goes through ``store``, so a recording store captures
    the exact witness path (reference `common/decode.rs:17-42`).
    """
    raw = store.get(state_root_cid)
    if raw is None:
        raise KeyError(f"missing StateRoot {state_root_cid}")
    state_root = StateRoot.decode(raw)
    actors = HAMT.load(store, state_root.actors, bit_width=HAMT_BIT_WIDTH)
    value = actors.get(address.to_bytes())
    if value is None:
        raise KeyError(f"actor not found for {address}")
    return ActorState.from_tuple(value)


@dataclass
class EvmStateLite:
    """The slice of EVM actor state the proofs need
    (reference `common/decode.rs:71-76`)."""

    bytecode: CID
    bytecode_hash: bytes
    contract_state: CID  # the storage HAMT root
    nonce: int


def parse_evm_state(raw: bytes) -> EvmStateLite:
    """Parse the EVM actor state tuple; 6-field first, 5-field fallback.

    v6: ``[bytecode, bytecode_hash, contract_state, reserved, nonce, tombstone]``
    v5: ``[bytecode, bytecode_hash, contract_state, nonce, tombstone]``
    """
    fields = cbor_decode(raw)
    if not isinstance(fields, list) or len(fields) not in (5, 6):
        raise ValueError(f"EVM state must be a 5/6-tuple, got {type(fields)}")
    if not (isinstance(fields[0], CID) and isinstance(fields[2], CID)):
        raise ValueError("EVM state fields 0/2 must be CIDs")
    if not (isinstance(fields[1], bytes) and len(fields[1]) == 32):
        raise ValueError("EVM state bytecode_hash must be 32 bytes")
    nonce = fields[4] if len(fields) == 6 else fields[3]
    if not isinstance(nonce, int):
        raise ValueError("EVM state nonce must be an int")
    return EvmStateLite(
        bytecode=fields[0],
        bytecode_hash=fields[1],
        contract_state=fields[2],
        nonce=nonce,
    )
