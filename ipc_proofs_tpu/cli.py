"""Command-line interface: generate / verify / demo.

The reference has no CLI at all — `main.rs` is a hardcoded end-to-end run
against calibration net (endpoint, height, contract, event all constants,
`src/main.rs:21-64`; SURVEY.md §5 lists "no config/flag system" as a gap).
This CLI exposes the same flow with real flags plus offline verification of
saved bundles.

    python -m ipc_proofs_tpu.cli generate --endpoint URL --height H \
        --contract 0x... --slot-subnet calib-subnet-1 --slot-index 0 \
        --event-sig "NewTopDownMessage(bytes32,uint256)" \
        --topic1 calib-subnet-1 --backend cpu -o bundle.json
    python -m ipc_proofs_tpu.cli verify bundle.json [--f3-cert cert.json] \
        [--event-sig ... --topic1 ...] [--check-cids]
    python -m ipc_proofs_tpu.cli demo          # hermetic synthetic-chain run
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ipc_proofs_tpu.utils.log import get_logger

log = get_logger(__name__)


def _make_rpc_client(args, metrics=None):
    """Build the chain client: one `LotusClient`, or an `EndpointPool`
    across ``--endpoint`` + every ``--endpoints`` replica (failover,
    circuit breakers, hedged fetches, per-endpoint integrity demotion).
    ``metrics`` routes RPC/pool counters into the caller's registry
    instead of each object's own private one."""
    from ipc_proofs_tpu.store.rpc import LotusClient

    endpoints = [args.endpoint] if args.endpoint else []
    for extra in getattr(args, "endpoints", None) or []:
        if extra not in endpoints:
            endpoints.append(extra)
    if not endpoints:
        raise ValueError("no RPC endpoint configured")
    clients = [
        LotusClient(
            e, bearer_token=args.token, timeout_s=args.timeout, metrics=metrics
        )
        for e in endpoints
    ]
    if len(clients) == 1:
        return clients[0]
    from ipc_proofs_tpu.store.failover import EndpointPool

    log.info(
        "endpoint pool: %d endpoints (breaker_threshold=%d hedge_ms=%s)",
        len(clients), args.breaker_threshold, args.hedge_ms,
    )
    return EndpointPool(
        clients,
        breaker_threshold=args.breaker_threshold,
        hedge_ms=args.hedge_ms,
        # serve/cluster only: generate/range runs have no --retry-budget
        retry_budget_per_s=getattr(args, "retry_budget", None),
        metrics=metrics,
    )


def _start_tracing(args) -> bool:
    """Enable the span collector when ``--trace-out``, ``--trace-otlp`` or
    ``--trace-otlp-url`` was given; ``--trace-sample`` head-samples whole
    traces at the collector (the always-on flight ring is unaffected)."""
    if not (
        getattr(args, "trace_out", None)
        or getattr(args, "trace_otlp", None)
        or getattr(args, "trace_otlp_url", None)
    ):
        return False
    from ipc_proofs_tpu.obs import enable_tracing

    enable_tracing(sample=getattr(args, "trace_sample", 1.0))
    return True


def _build_slo_watchdog(args, metrics):
    """`--slo on` → a configured (not yet started) burn-rate watchdog."""
    from ipc_proofs_tpu.obs.slo import SloWatchdog, default_targets

    return SloWatchdog(
        metrics=metrics,
        targets=default_targets(
            availability=args.slo_availability,
            generate_p99_ms=args.slo_generate_p99_ms,
            delivery_lag_p99_ms=args.slo_delivery_lag_p99_ms,
        ),
        fast_window_s=args.slo_fast_window_s,
        slow_window_s=args.slo_slow_window_s,
        interval_s=args.slo_interval_s,
    )


def _finish_tracing(args) -> None:
    """Export collected spans to ``--trace-out`` (Chrome trace JSON, load
    at ui.perfetto.dev or chrome://tracing) and/or ``--trace-otlp``
    (OTLP/JSON file), and/or POST them to a live collector at
    ``--trace-otlp-url`` (retried, fail-soft)."""
    from ipc_proofs_tpu.obs import (
        disable_tracing,
        get_collector,
        write_chrome_trace,
        write_otlp_trace,
    )

    collector = get_collector()
    spans = collector.snapshot() if collector is not None else []
    dropped = collector.dropped if collector is not None else 0
    disable_tracing()
    if getattr(args, "trace_out", None):
        n = write_chrome_trace(args.trace_out, spans)
        log.info(
            "trace: %d events → %s%s", n, args.trace_out,
            f" ({dropped} spans dropped at capacity)" if dropped else "",
        )
    if getattr(args, "trace_otlp", None):
        n = write_otlp_trace(args.trace_otlp, spans)
        log.info("trace: %d spans → %s (OTLP/JSON)", n, args.trace_otlp)
    if getattr(args, "trace_otlp_url", None):
        from ipc_proofs_tpu.obs.export import post_otlp_trace

        if post_otlp_trace(args.trace_otlp_url, spans):
            log.info(
                "trace: %d spans POSTed → %s", len(spans), args.trace_otlp_url
            )


def _cmd_generate(args) -> int:
    from ipc_proofs_tpu.backend import get_backend
    from ipc_proofs_tpu.proofs.address import resolve_eth_address_to_actor_id
    from ipc_proofs_tpu.proofs.chain import Tipset
    from ipc_proofs_tpu.proofs.generator import (
        EventProofSpec,
        StorageProofSpec,
        generate_proof_bundle,
    )
    from ipc_proofs_tpu.state.storage import calculate_storage_slot
    from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
    from ipc_proofs_tpu.utils.metrics import get_metrics

    metrics = get_metrics()
    tracing = _start_tracing(args)
    client = _make_rpc_client(args, metrics=metrics)

    with metrics.stage("fetch_tipsets"):
        parent = Tipset.fetch(client, args.height)
        child = Tipset.fetch(client, args.height + 1)
    log.info("parent tipset @%d: %d blocks", parent.height, len(parent.cids))

    with metrics.stage("resolve_address"):
        actor_id = (
            args.actor_id
            if args.actor_id is not None
            else resolve_eth_address_to_actor_id(client, args.contract)
        )
    log.info("actor id: %d", actor_id)

    storage_specs = []
    if args.slot_subnet is not None:
        slot = calculate_storage_slot(args.slot_subnet, args.slot_index)
        storage_specs.append(StorageProofSpec(actor_id=actor_id, slot=slot))
    event_specs = []
    if args.event_sig:
        event_specs.append(
            EventProofSpec(
                event_signature=args.event_sig,
                topic_1=args.topic1,
                actor_id_filter=None if args.no_actor_filter else actor_id,
            )
        )

    store = RpcBlockstore(client)
    backend = get_backend(args.backend) if args.backend != "none" else None
    with metrics.stage("generate"):
        bundle = generate_proof_bundle(
            store, parent, child, storage_specs, event_specs, match_backend=backend,
            receipts_client=client if args.receipts_api else None,
        )

    output = args.output or "bundle.json"
    with open(output, "w") as fh:
        fh.write(bundle.to_json(indent=2))
    log.info(
        "bundle: %d storage + %d event proofs, %d witness blocks (%d bytes) → %s",
        len(bundle.storage_proofs), len(bundle.event_proofs),
        len(bundle.blocks), bundle.witness_bytes(), output,
    )
    if args.metrics:
        print(metrics.to_json(), file=sys.stderr)
    if tracing:
        _finish_tracing(args)
    return 0


def _cmd_verify(args) -> int:
    from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
    from ipc_proofs_tpu.proofs.cert import FinalityCertificate
    from ipc_proofs_tpu.proofs.event_verifier import create_event_filter
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle

    with open(args.bundle) as fh:
        bundle = UnifiedProofBundle.from_json(fh.read())

    if args.f3_cert:
        with open(args.f3_cert) as fh:
            cert = FinalityCertificate.from_json_obj(json.load(fh))
        policy = TrustPolicy.with_f3_certificate(cert)
    else:
        log.warning("no F3 certificate — accept-all trust (testing only)")
        policy = TrustPolicy.accept_all()

    event_filter = (
        create_event_filter(args.event_sig, args.topic1) if args.event_sig else None
    )

    start = time.perf_counter()
    result = verify_proof_bundle(
        bundle, policy, event_filter=event_filter, verify_witness_cids=args.check_cids
    )
    elapsed = time.perf_counter() - start

    print(
        json.dumps(
            {
                "storage_results": result.storage_results,
                "event_results": result.event_results,
                "all_valid": result.all_valid(),
                "verify_seconds": round(elapsed, 4),
            }
        )
    )
    return 0 if result.all_valid() else 1


def _cmd_range(args) -> int:
    """Event proofs across a whole epoch range, chunked + resumable."""
    from ipc_proofs_tpu.backend import get_backend
    from ipc_proofs_tpu.proofs.address import resolve_eth_address_to_actor_id
    from ipc_proofs_tpu.proofs.chain import Tipset
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import (
        TipsetPair,
        generate_event_proofs_for_range_chunked,
    )
    from ipc_proofs_tpu.store.rpc import LotusClient, RpcBlockstore
    from ipc_proofs_tpu.utils.metrics import get_metrics

    if args.storage_slot and not args.contract:
        # validate before any network work — the tipset fetch below can be
        # tens of thousands of RPC calls
        log.error("--storage-slot requires --contract")
        return 2
    if args.resume:
        # --resume is an assertion that a journaled job already exists; a
        # typo'd --job-dir must fail loudly, not silently start from scratch
        import os as _os

        from ipc_proofs_tpu.jobs import JOBS_MANIFEST_NAME

        if not args.job_dir:
            log.error("--resume requires --job-dir")
            return 2
        if not _os.path.exists(_os.path.join(args.job_dir, JOBS_MANIFEST_NAME)):
            log.error(
                "--resume: no job manifest in %s (nothing to resume)", args.job_dir
            )
            return 2

    metrics = get_metrics()
    tracing = _start_tracing(args)
    client = _make_rpc_client(args, metrics=metrics)

    actor_id = None
    if args.contract:
        actor_id = resolve_eth_address_to_actor_id(client, args.contract)
        log.info("actor id: %d", actor_id)

    with metrics.stage("fetch_tipsets"):
        tipsets = [Tipset.fetch(client, h) for h in range(args.from_height, args.to_height + 2)]
    pairs = [
        TipsetPair(parent=tipsets[i], child=tipsets[i + 1]) for i in range(len(tipsets) - 1)
    ]
    log.info("range: %d tipset pairs", len(pairs))

    spec = EventProofSpec(
        event_signature=args.event_sig, topic_1=args.topic1, actor_id_filter=actor_id
    )
    storage_specs = None
    if args.storage_slot:
        from ipc_proofs_tpu.proofs.storage_batch import MappingSlotSpec

        storage_specs = [
            MappingSlotSpec(actor_id=actor_id, key=key, slot_index=args.slot_index)
            for key in args.storage_slot
        ]
    backend = (
        get_backend(args.backend, mesh_devices=args.mesh_devices)
        if args.backend != "none"
        else None
    )
    if backend is not None and getattr(backend, "mesh", None) is not None:
        log.info("mesh-sharded matching: %d device(s)", backend.mesh.size)
    from ipc_proofs_tpu.utils.profiling import maybe_profile

    generate_fn = None
    if args.pipeline_depth > 0:
        # stage-overlapped engine per checkpoint chunk: each outer chunk
        # splits into sub-chunks so scan workers overlap recording while
        # checkpointing (and resume) stay at --chunk-size granularity
        import functools

        from ipc_proofs_tpu.proofs.range import (
            generate_event_proofs_for_range_pipelined,
        )
        from ipc_proofs_tpu.utils.threads import resolve_thread_budget

        budget = resolve_thread_budget(
            threads=args.threads, scan_threads=args.scan_threads
        )
        generate_fn = functools.partial(
            generate_event_proofs_for_range_pipelined,
            chunk_size=max(1, args.chunk_size // max(2, budget.total)),
            scan_threads=args.scan_threads,
            threads=args.threads,
            pipeline_depth=args.pipeline_depth,
        )

    plane = None
    if args.batch_rpc:
        from ipc_proofs_tpu.store.fetchplane import FetchPlane, PlaneBlockstore

        plane = FetchPlane(
            client,
            speculate_depth=args.speculate_depth,
            metrics=metrics,
            batch_verify=args.batch_verify,
        )
        store = PlaneBlockstore(plane)
        log.info(
            "fetch plane: batched RPC, speculate_depth=%s", args.speculate_depth
        )
    else:
        store = RpcBlockstore(client)
    disk = None
    if args.store_dir:
        from ipc_proofs_tpu.storex import SegmentStore, TieredBlockstore

        disk = SegmentStore(
            args.store_dir,
            cap_bytes=args.store_cap_bytes,
            segment_max_bytes=args.store_segment_max_bytes,
            metrics=metrics,
            batch_verify=args.batch_verify,
        )
        store = TieredBlockstore(store, disk, metrics=metrics)
        if plane is not None:
            # tier short-circuit: wants already on disk never hit RPC
            plane.set_local(store)
        log.info("disk tier: %s (%s)", args.store_dir, disk.stats())

    with maybe_profile(args.profile):
        bundle = generate_event_proofs_for_range_chunked(
            store,
            pairs,
            spec,
            chunk_size=args.chunk_size,
            checkpoint_dir=args.checkpoint_dir,
            match_backend=backend,
            metrics=metrics,
            storage_specs=storage_specs,
            scan_workers=args.scan_workers,
            generate_fn=generate_fn,
            job_dir=args.job_dir,
        )
    output = args.output or "range_bundle.json"
    with open(output, "w") as fh:
        fh.write(bundle.to_json())
    log.info(
        "range bundle: %d event + %d storage proofs, %d witness blocks → %s",
        len(bundle.event_proofs), len(bundle.storage_proofs), len(bundle.blocks), output,
    )
    if plane is not None:
        plane.close()
        log.info("fetch plane: %s", plane.stats())
    if disk is not None:
        disk.close()
    if args.metrics:
        print(metrics.to_json(), file=sys.stderr)
    if tracing:
        _finish_tracing(args)
    return 0


def _cmd_backfill(args) -> int:
    """Prove deep history as a durable batch job (`ipc_proofs_tpu.backfill`).

    Two store modes mirroring ``range``/``serve``:
    - ``--demo-world N``: hermetic synthetic range world (tests, CI);
    - ``--endpoint`` + ``--from-height/--to-height``: live chain.

    The range splits into ``--window-size`` epoch windows; each committed
    window journals under ``--jobs-dir`` (re-running the identical
    command resumes instead of re-proving) and streams as one log line
    the moment it lands — long before the job completes. The sealed
    bundle is byte-identical to the ``range`` command over the same pairs.
    """
    from ipc_proofs_tpu.backend import get_backend
    from ipc_proofs_tpu.backfill import BackfillEngine, local_window_runner
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import TipsetPair
    from ipc_proofs_tpu.utils.metrics import get_metrics

    metrics = get_metrics()
    tracing = _start_tracing(args)

    plane = None
    disk = None
    if args.demo_world:
        from ipc_proofs_tpu.fixtures import build_range_world

        sig = args.event_sig or "NewTopDownMessage(bytes32,uint256)"
        topic1 = args.topic1 or "calib-subnet-1"
        store, pairs, n_matching = build_range_world(
            args.demo_world,
            receipts_per_pair=args.demo_receipts,
            match_rate=args.demo_match_rate,
            signature=sig,
            topic1=topic1,
        )
        spec = EventProofSpec(event_signature=sig, topic_1=topic1)
        log.info(
            "demo world: %d pairs, %d matching events", len(pairs), n_matching
        )
    else:
        from ipc_proofs_tpu.proofs.address import resolve_eth_address_to_actor_id
        from ipc_proofs_tpu.proofs.chain import Tipset
        from ipc_proofs_tpu.store.rpc import RpcBlockstore

        if not args.endpoint:
            log.error("backfill needs --demo-world or --endpoint")
            return 2
        if args.from_height is None or args.to_height is None:
            log.error("--endpoint requires --from-height and --to-height")
            return 2
        if not (args.event_sig and args.topic1):
            log.error("--endpoint requires --event-sig and --topic1")
            return 2
        client = _make_rpc_client(args, metrics=metrics)
        actor_id = None
        if args.contract:
            actor_id = resolve_eth_address_to_actor_id(client, args.contract)
            log.info("actor id: %d", actor_id)
        with metrics.stage("fetch_tipsets"):
            tipsets = [
                Tipset.fetch(client, h)
                for h in range(args.from_height, args.to_height + 2)
            ]
        pairs = [
            TipsetPair(parent=tipsets[i], child=tipsets[i + 1])
            for i in range(len(tipsets) - 1)
        ]
        spec = EventProofSpec(
            event_signature=args.event_sig,
            topic_1=args.topic1,
            actor_id_filter=actor_id,
        )
        if args.batch_rpc:
            from ipc_proofs_tpu.store.fetchplane import FetchPlane, PlaneBlockstore

            plane = FetchPlane(
                client,
                speculate_depth=args.speculate_depth,
                metrics=metrics,
                batch_verify=args.batch_verify,
            )
            store = PlaneBlockstore(plane)
        else:
            store = RpcBlockstore(client)
        if args.store_dir:
            from ipc_proofs_tpu.storex import SegmentStore, TieredBlockstore

            disk = SegmentStore(
                args.store_dir,
                cap_bytes=args.store_cap_bytes,
                segment_max_bytes=args.store_segment_max_bytes,
                metrics=metrics,
                batch_verify=args.batch_verify,
            )
            store = TieredBlockstore(store, disk, metrics=metrics)
            if plane is not None:
                plane.set_local(store)

    start = args.pair_start
    end = args.pair_end if args.pair_end is not None else len(pairs)
    if not (0 <= start < end <= len(pairs)):
        log.error(
            "pair range [%d, %d) out of bounds for %d pairs",
            start, end, len(pairs),
        )
        return 2

    backend = (
        get_backend(args.backend, mesh_devices=args.mesh_devices)
        if args.backend != "none"
        else None
    )
    engine = BackfillEngine(
        pairs,
        spec,
        local_window_runner(
            store, spec, chunk_size=args.chunk_size,
            match_backend=backend, metrics=metrics,
        ),
        jobs_dir=args.jobs_dir,
        window_size=args.window_size,
        work_ahead=args.work_ahead,
        window_parallelism=args.window_parallelism,
        plane=plane,
        metrics=metrics,
    )
    rc = 0
    try:
        job = engine.submit(start, end)
        log.info(
            "backfill %s: %d epochs in %d windows of %d (jobs dir: %s)",
            job.job_id, end - start, len(job.windows), job.window_size,
            args.jobs_dir or "none — not resumable",
        )
        cursor = 0
        while True:
            resp = job.chunks_after(cursor, wait_s=5.0)
            for chunk in resp["chunks"]:
                w = chunk["window"]
                log.info(
                    "chunk %d/%d: window %d pairs [%d, %d) — %d proofs (%s)",
                    chunk["cursor"], len(job.windows), w["index"],
                    w["lo"], w["hi"], chunk["n_event_proofs"], chunk["digest"],
                )
                cursor = chunk["cursor"]
            if resp["state"] != "running" and not resp["chunks"]:
                break
        bundle = job.result(timeout=0)
        output = args.output or "backfill_bundle.json"
        with open(output, "w") as fh:
            fh.write(bundle.to_json())
        status = job.status()
        log.info(
            "backfill %s complete: %d event proofs, %d witness blocks → %s "
            "(%d/%d windows replayed from journal, first chunk %.2fs, "
            "total %.2fs)",
            job.job_id, len(bundle.event_proofs), len(bundle.blocks), output,
            status["windows_replayed"], status["windows_total"],
            status["first_chunk_s"] or 0.0, status["wall_s"],
        )
    except Exception as exc:  # fail-soft: CLI exit path — report + rc 1
        log.error("backfill failed: %s", exc)
        rc = 1
    finally:
        engine.close()
        if plane is not None:
            plane.close()
        if disk is not None:
            disk.close()
    if args.metrics:
        print(metrics.to_json(), file=sys.stderr)
    if tracing:
        _finish_tracing(args)
    return rc


def _cmd_vectors(args) -> int:
    """Capture live-chain byte-compat vectors (headers, TxMeta,
    receipts-AMT root) into a fixtures JSON the test suite consumes —
    grounds the codecs against real chain bytes the way the reference's
    live run does implicitly (`src/main.rs:19-101`)."""
    from ipc_proofs_tpu.proofs.vectors import capture_vectors, check_vectors, write_vectors
    from ipc_proofs_tpu.store.rpc import LotusClient

    client = _make_rpc_client(args)
    doc = capture_vectors(client, args.height)
    n = check_vectors(doc)  # never write vectors we cannot re-verify
    output = args.output or "vectors.json"
    write_vectors(doc, output)
    log.info("captured %d vectors at height %d → %s", n, args.height, output)
    log.info("re-run the byte-compat suite with IPC_VECTORS_FILE=%s", output)
    return 0


def _cmd_cert(args) -> int:
    """Inspect / validate F3 finality certificates (JSON or go-f3 CBOR)."""
    from ipc_proofs_tpu.proofs.cert import (
        FinalityCertificate,
        FinalityCertificateChain,
        PowerTableEntry,
    )
    from ipc_proofs_tpu.proofs.cert_cbor import (
        certificate_from_cbor,
        certificate_to_cbor,
    )

    def load_cert(path: str) -> FinalityCertificate:
        with open(path, "rb") as fh:
            raw = fh.read()
        # JSON certificates are Forest-style objects; anything that does
        # not parse as a JSON object is treated as certexchange CBOR
        try:
            obj = json.loads(raw)
        except ValueError:
            return certificate_from_cbor(raw)
        return FinalityCertificate.from_json_obj(obj)

    certs = [load_cert(p) for p in args.certificates]
    chain = FinalityCertificateChain(certificates=certs)

    table = None
    if args.power_table:
        with open(args.power_table) as fh:
            rows = json.load(fh)
        if not isinstance(rows, list):
            raise SystemExit("power table JSON must be a list of rows")
        table = [
            PowerTableEntry(
                participant_id=int(r["ParticipantID"]),
                power=int(r["Power"]),
                signing_key=str(r["SigningKey"]),
                pop=str(r.get("Pop", "")),
            )
            for r in rows
        ]

    if args.verify_signatures and table is None:
        raise SystemExit("--verify-signatures requires --power-table")

    if args.emit_cbor:
        if len(certs) != 1:
            raise SystemExit("--emit-cbor takes exactly one certificate")
        with open(args.emit_cbor, "wb") as fh:
            fh.write(certificate_to_cbor(certs[0]))
        log.info("wrote certexchange CBOR → %s", args.emit_cbor)

    status = "ok"
    error = None
    final_table_size = None
    try:
        final = chain.validate(
            initial_power_table=table,
            verify_signatures=args.verify_signatures,
            verify_table_cids=table is not None,
            network=args.network,
        )
        final_table_size = len(final) if final is not None else None
    except ValueError as exc:
        status, error = "invalid", str(exc)

    print(
        json.dumps(
            {
                "certificates": len(certs),
                "instances": [c.instance for c in certs],
                "epochs": [
                    [c.ec_chain[0].epoch, c.ec_chain[-1].epoch] if c.ec_chain else None
                    for c in certs
                ],
                "signatures_verified": bool(args.verify_signatures) and status == "ok",
                "final_power_table_rows": final_table_size,
                "status": status,
                "error": error,
            }
        )
    )
    return 0 if status == "ok" else 1


def _cmd_demo(args) -> int:
    """The reference `main.rs` flow, hermetic: synthesize a chain, generate
    one storage + one event proof, verify offline, print results."""
    from ipc_proofs_tpu.fixtures import ContractFixture, EventFixture, build_chain
    from ipc_proofs_tpu.proofs.event_verifier import create_event_filter
    from ipc_proofs_tpu.proofs.generator import (
        EventProofSpec,
        StorageProofSpec,
        generate_proof_bundle,
    )
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.proofs.verifier import verify_proof_bundle
    from ipc_proofs_tpu.state.storage import calculate_storage_slot

    sig = "NewTopDownMessage(bytes32,uint256)"
    subnet = "calib-subnet-1"
    actor = 1001
    slot = calculate_storage_slot(subnet, 0)

    world = build_chain(
        [ContractFixture(actor_id=actor, storage={slot: (15).to_bytes(1, "big")})],
        [
            [EventFixture(emitter=actor, signature=sig, topic1=subnet, data=b"\x0f".rjust(32, b"\x00"))],
            [],
            [EventFixture(emitter=actor, signature=sig, topic1=subnet, data=b"\x10".rjust(32, b"\x00"))],
        ],
        parent_height=2_992_953,
    )
    bundle = generate_proof_bundle(
        world.store,
        world.parent,
        world.child,
        [StorageProofSpec(actor_id=actor, slot=slot)],
        [EventProofSpec(event_signature=sig, topic_1=subnet, actor_id_filter=actor)],
    )
    print("Unified Proof Bundle generated:")
    print(f"  Storage proofs: {len(bundle.storage_proofs)}")
    print(f"  Event proofs: {len(bundle.event_proofs)}")
    print(f"  Total witness blocks: {len(bundle.blocks)}")

    result = verify_proof_bundle(
        bundle,
        TrustPolicy.accept_all(),
        event_filter=create_event_filter(sig, subnet),
        verify_witness_cids=True,
    )
    print("Verification Results:")
    print(f"  Storage proofs valid: {result.storage_results}")
    print(f"  Event proofs valid: {result.event_results}")
    print(f"  All valid: {result.all_valid()}")
    return 0 if result.all_valid() else 1


def _parse_tenant_weights(specs) -> "dict | None":
    """Parse repeated ``--tenant-weight name=N`` into ``{name: N}``.
    Returns None when no weights were given (the FairQueue default —
    every tenant weighs 1). Bad specs abort with exit code 2."""
    if not specs:
        return None
    out: "dict[str, int]" = {}
    for spec in specs:
        name, sep, num = str(spec).partition("=")
        try:
            weight = int(num)
        except ValueError:
            weight = 0
        if not sep or not name or weight < 1:
            raise SystemExit(
                f"--tenant-weight must be name=N with N >= 1 (got {spec!r})"
            )
        out[name] = weight
    return out


def _cmd_serve(args) -> int:
    """Long-running proof service: micro-batched verify/generate over HTTP.

    Three store modes:
    - default: verify-only (``POST /v1/verify`` + ``/metrics``/``/healthz``);
    - ``--demo-world N``: hermetic synthetic range world with N tipset
      pairs — enables ``POST /v1/generate {"pair_index": i}`` with no
      network egress (the serving analogue of ``demo``);
    - ``--endpoint`` + ``--from-height/--to-height``: RPC-backed store,
      pair table fetched from the chain (requires ``--event-sig/--topic1``).
    """
    import signal

    from ipc_proofs_tpu.proofs.event_verifier import create_event_filter
    from ipc_proofs_tpu.proofs.generator import EventProofSpec
    from ipc_proofs_tpu.proofs.range import TipsetPair
    from ipc_proofs_tpu.proofs.trust import TrustPolicy
    from ipc_proofs_tpu.serve import ProofHTTPServer, ProofService, ServiceConfig
    from ipc_proofs_tpu.utils.metrics import Metrics

    # the service owns its metrics registry (not the process-global one) so
    # /metrics reflects exactly this server; the RPC client/pool feed it too
    metrics = Metrics()
    tracing = _start_tracing(args)

    store, pairs, spec = None, [], None
    if args.demo_world:
        from ipc_proofs_tpu.fixtures import build_range_world

        sig = args.event_sig or "NewTopDownMessage(bytes32,uint256)"
        topic1 = args.topic1 or "calib-subnet-1"
        store, pairs, n_matching = build_range_world(
            args.demo_world,
            receipts_per_pair=args.demo_receipts,
            match_rate=args.demo_match_rate,
            signature=sig,
            topic1=topic1,
        )
        spec = EventProofSpec(event_signature=sig, topic_1=topic1)
        log.info(
            "demo world: %d pairs, %d matching events", len(pairs), n_matching
        )
    endpoint_pool = None
    client = None
    if not args.demo_world and (args.endpoint or args.endpoints):
        from ipc_proofs_tpu.proofs.chain import Tipset
        from ipc_proofs_tpu.store.failover import EndpointPool
        from ipc_proofs_tpu.store.rpc import RpcBlockstore

        if args.from_height is None or args.to_height is None:
            log.error("--endpoint requires --from-height and --to-height")
            return 2
        if not (args.event_sig and args.topic1):
            log.error("--endpoint requires --event-sig and --topic1")
            return 2
        client = _make_rpc_client(args, metrics=metrics)
        if isinstance(client, EndpointPool):
            endpoint_pool = client  # /healthz reports per-endpoint breakers
        tipsets = [
            Tipset.fetch(client, h)
            for h in range(args.from_height, args.to_height + 2)
        ]
        pairs = [
            TipsetPair(parent=tipsets[i], child=tipsets[i + 1])
            for i in range(len(tipsets) - 1)
        ]
        store = RpcBlockstore(client)
        spec = EventProofSpec(
            event_signature=args.event_sig, topic_1=args.topic1
        )

    if args.f3_cert:
        from ipc_proofs_tpu.proofs.cert import FinalityCertificate

        with open(args.f3_cert) as fh:
            cert = FinalityCertificate.from_json_obj(json.load(fh))
        policy = TrustPolicy.with_f3_certificate(cert)
    else:
        log.warning("no F3 certificate — accept-all trust (testing only)")
        policy = TrustPolicy.accept_all()

    service = ProofService(
        store=store,
        spec=spec,
        trust_policy=policy,
        event_filter=(
            create_event_filter(args.event_sig, args.topic1)
            if args.event_sig and args.topic1
            else None
        ),
        config=ServiceConfig(
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_capacity=args.queue_capacity,
            workers=args.workers,
            cache_max_bytes=args.cache_max_bytes,
            cache_ttl_s=args.cache_ttl_s,
            verify_witness_cids=args.check_cids,
            range_scan_threads=args.scan_threads,
            range_pipeline_depth=args.pipeline_depth,
            threads=args.threads,
            slow_request_ms=args.slow_ms,
            store_dir=args.store_dir,
            store_cap_bytes=args.store_cap_bytes,
            store_segment_max_bytes=args.store_segment_max_bytes,
            store_owner=args.store_owner,
            batch_rpc=args.batch_rpc,
            speculate_depth=args.speculate_depth,
            match_backend=(None if args.backend == "none" else args.backend),
            mesh_devices=args.mesh_devices,
            batch_verify=args.batch_verify,
            witness_delta=(args.witness_delta == "on"),
            witness_compress=(args.witness_compress == "on"),
            witness_agg_max=args.witness_agg_max,
            witness_base_cache=args.witness_base_cache,
            tenant_rate=args.tenant_rate,
            tenant_burst=args.tenant_burst,
            tenant_weights=_parse_tenant_weights(args.tenant_weight),
            admit_gradient=args.admit_gradient,
            admit_delay_budget_ms=args.admit_delay_budget_ms,
            deadline_floor_ms=args.deadline_floor_ms,
            retry_budget=args.retry_budget,
            registry_dir=args.registry_dir,
            registry_owner=args.registry_owner,
            registry_fsync=(args.registry_fsync == "on"),
        ),
        endpoint_pool=endpoint_pool,
        metrics=metrics,
    )
    follower = None
    leader_lock = None
    if args.follow:
        if client is None or service.blockstore is None:
            log.error("--follow requires --endpoint (a chain to follow)")
            service.drain()
            return 2
        from ipc_proofs_tpu.storex import ChainFollower, FollowLeaderLock

        lead = True
        if args.store_dir:
            # shared disk tier → exactly one follower per cluster: the
            # flock winner tails the chain for everyone, losers serve only
            # (and the kernel hands the lock to a successor if we die)
            leader_lock = FollowLeaderLock(args.store_dir)
            lead = leader_lock.try_acquire(metrics=metrics)
        if lead:
            follower = ChainFollower(
                client,
                service.blockstore,
                metrics=metrics,
                poll_s=args.follow_poll_s,
                batch_verify=args.batch_verify,
            )
            follower.start()
            log.info(
                "chain follower: tailing finalized tipsets every %.1fs%s",
                args.follow_poll_s,
                " (elected leader)" if args.store_dir else "",
            )
        else:
            log.info(
                "chain follower: another shard leads (%s) — serving only",
                leader_lock.path,
            )
    durable = None
    if args.queue_dir:
        from ipc_proofs_tpu.serve.durable import DurableAdmission

        durable = DurableAdmission(
            service,
            args.queue_dir,
            pairs=pairs,
            results_max_bytes=args.results_cache_bytes,
        )
        if durable.resumed_jobs:
            log.info(
                "durable queue: re-executed %d admitted-but-unfinished "
                "request(s) from %s", durable.resumed_jobs, args.queue_dir,
            )
    subs = None
    if args.subs_dir:
        from ipc_proofs_tpu.subs import StandingQueries

        if service.blockstore is None:
            log.error("--subs-dir needs a store (--demo-world or --endpoint)")
            service.drain()
            return 2
        subs = StandingQueries(
            args.subs_dir,
            store=service.blockstore,
            metrics=metrics,
            chunk_size=service.config.range_chunk_size,
            match_backend=service.match_backend,
            log_cap_bytes=args.subs_log_cap_bytes,
            push_max_inflight=args.push_max_inflight,
            retry_attempts=args.delivery_retry_attempts,
            retry_base_s=args.delivery_retry_base_s,
            retry_max_s=args.delivery_retry_max_s,
            delta=(args.witness_delta == "on"),
            # generate-capable service → standing-query generations ride
            # the batcher's PUSH lane (one priority order with
            # interactive requests and backfill windows)
            service=(service if spec is not None and store is not None else None),
            # fleet base directory: pushed bundles + acked bases seal into
            # the provenance chain so deltas survive failover fleet-wide
            provenance=service.registry,
            fleet=args.subs_fleet,
        )
        if subs.registry.replayed:
            log.info(
                "standing queries: %d subscription(s) active after replay, "
                "%d unacked delivery(ies) re-pushing",
                len(subs.registry), subs.log.pending_total(),
            )
        if follower is not None:
            # the streaming plane: each finalized tipset the (leader)
            # follower warms also drives match → generate-once → fan-out
            follower.add_finalized_hook(subs.on_tipset)
    slo = None
    if args.slo == "on":
        slo = _build_slo_watchdog(args, metrics)
        slo.start()
    backfill = None
    if args.backfill_jobs_dir:
        if spec is None or store is None or not pairs:
            log.error(
                "--backfill-jobs-dir needs a generate-capable service "
                "(--demo-world or --endpoint)"
            )
            service.drain()
            return 2
        from ipc_proofs_tpu.backfill import BackfillEngine

        def _run_backfill_window(window, wpairs):
            # LOW lane: a backfill window only dispatches when the
            # interactive verify/generate queue is empty
            return service.submit_range_window(wpairs).result()

        backfill = BackfillEngine(
            pairs,
            spec,
            _run_backfill_window,
            jobs_dir=args.backfill_jobs_dir,
            window_size=args.backfill_window_size,
            plane=service.fetch_plane,
            metrics=metrics,
            delivery=(subs.log if subs is not None else None),
        )
        log.info(
            "backfill: /v1/backfill mounted (jobs dir %s, window %d)",
            args.backfill_jobs_dir, args.backfill_window_size,
        )
    from ipc_proofs_tpu.obs.fleet import TenantLedger

    httpd = ProofHTTPServer(
        service, host=args.host, port=args.port, pairs=pairs, durable=durable,
        subs=subs, slo=slo,
        tenants=TenantLedger(metrics=metrics, top_k=args.tenant_top_k),
        backfill=backfill,
    )
    if args.port_file:
        # atomic write: a polling parent never reads a half-written port
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(str(httpd.port))
        os.replace(tmp, args.port_file)
    log.info(
        "serving on %s (verify%s; max_batch=%d max_wait=%.1fms capacity=%d "
        "workers=%d)",
        httpd.address,
        " + generate" if spec is not None and store is not None else " only",
        args.max_batch, args.max_wait_ms, args.queue_capacity, args.workers,
    )

    def _sigterm(_signum, _frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        log.info("draining (flushing accepted requests)…")
    finally:
        if follower is not None:
            follower.stop()
        httpd.shutdown()
        if leader_lock is not None:
            leader_lock.release()
        if tracing:
            _finish_tracing(args)
    log.info("drained; final metrics:\n%s", json.dumps(service.metrics_snapshot()))
    return 0


def _cmd_cluster(args) -> int:
    """Sharded serve plane: spawn N serve shards + the consistent-hash
    router, all over one hermetic ``--demo-world``.

    Each shard is a full ``serve`` child process (own GIL, own durable
    queue under ``--queue-dir/s<k>``, own ``--store-owner`` token in the
    shared ``--store-dir``); the router front door speaks the exact
    single-daemon wire protocol, so existing clients work unchanged.
    """
    import signal

    from ipc_proofs_tpu.cluster import (
        ClusterRouter,
        RemoteShard,
        RouterHTTPServer,
        spawn_serve_shard,
    )
    from ipc_proofs_tpu.fixtures import build_range_world
    from ipc_proofs_tpu.utils.metrics import Metrics

    shard_urls = list(args.shard_url or ())
    if args.shards < 1 and not shard_urls:
        log.error("--shards must be >= 1 (or give at least one --shard-url)")
        return 2
    if args.shards < 0:
        log.error("--shards must be >= 0")
        return 2
    if not args.demo_world:
        log.error("cluster currently requires --demo-world (hermetic mode)")
        return 2
    tenant_weights = _parse_tenant_weights(args.tenant_weight)

    metrics = Metrics()
    tracing = _start_tracing(args)
    sig = args.event_sig or "NewTopDownMessage(bytes32,uint256)"
    topic1 = args.topic1 or "calib-subnet-1"
    # the router needs the pair table the shards will rebuild — the world
    # builder is deterministic, so building it here yields the same table
    _store, pairs, _n = build_range_world(
        args.demo_world,
        receipts_per_pair=args.demo_receipts,
        match_rate=args.demo_match_rate,
        signature=sig,
        topic1=topic1,
    )

    extra: "list[str]" = [
        "--demo-receipts", str(args.demo_receipts),
        "--demo-match-rate", str(args.demo_match_rate),
    ]
    if tracing:
        # the shards must run their span collector too so sampled requests
        # ship their subtree back for stitching (the router grafts them
        # under its dispatch spans); the shard-side export goes nowhere
        extra += [
            "--trace-out", os.devnull,
            "--trace-sample", str(getattr(args, "trace_sample", 1.0)),
        ]
    if args.store_cap_bytes is not None:
        extra += ["--store-cap-bytes", str(args.store_cap_bytes)]
    if args.store_segment_max_bytes is not None:
        extra += ["--store-segment-max-bytes", str(args.store_segment_max_bytes)]
    # witness diet knobs are cluster-wide: every shard must negotiate the
    # same encodings or the router's scatter-gather sees mixed wire shapes
    extra += [
        "--witness-delta", args.witness_delta,
        "--witness-compress", args.witness_compress,
        "--witness-agg-max", str(args.witness_agg_max),
        "--witness-base-cache", str(args.witness_base_cache),
    ]
    if tenant_weights:
        # fair-lane weights apply where the queues live: in each shard's
        # batcher (the router door throttles, shards order)
        for name, weight in sorted(tenant_weights.items()):
            extra += ["--tenant-weight", f"{name}={weight}"]
    if args.subs_dir:
        # push/retry knobs are cluster-wide; the registry itself shards
        # per process (DIR/s<k>) and the router places subscriptions on
        # their filter-affine arc
        extra += [
            "--push-max-inflight", str(args.push_max_inflight),
            "--delivery-retry-attempts", str(args.delivery_retry_attempts),
            "--delivery-retry-base-s", str(args.delivery_retry_base_s),
            "--delivery-retry-max-s", str(args.delivery_retry_max_s),
            "--subs-log-cap-bytes", str(args.subs_log_cap_bytes),
        ]

    shards = []
    try:
        for k in range(args.shards):
            name = f"s{k}"
            shard_extra = list(extra)
            if args.subs_dir:
                shard_extra += [
                    "--subs-dir", os.path.join(args.subs_dir, name)
                ]
            if args.registry_dir:
                # ONE shared provenance/base directory, one single-writer
                # log per shard (reg-s<k>.log) — this sharing is what lets
                # any shard answer for a base another shard served
                shard_extra += [
                    "--registry-dir", args.registry_dir,
                    "--registry-owner", name,
                    "--registry-fsync", args.registry_fsync,
                    "--subs-fleet", args.subs_fleet,
                ]
            shards.append(
                spawn_serve_shard(
                    name,
                    args.demo_world,
                    sig,
                    topic1,
                    store_dir=args.store_dir,
                    queue_dir=(
                        os.path.join(args.queue_dir, name)
                        if args.queue_dir
                        else None
                    ),
                    extra_args=shard_extra,
                )
            )
            log.info("shard %s up at %s", name, shards[-1].url)
    except RuntimeError as exc:
        log.error("shard spawn failed: %s", exc)
        for sh in shards:
            sh.kill()
        return 1

    # multi-host members: daemons someone else runs, probed before
    # admission so a typo'd URL fails loudly at boot instead of as a
    # string of failovers under traffic
    for url in shard_urls:
        member = RemoteShard(url)
        health = member.probe()
        if health is None:
            log.error("remote shard %s is unreachable — not admitted", url)
            for sh in shards:
                sh.kill()
            return 1
        log.info(
            "remote shard %s up at %s (status=%s)",
            member.name, member.url, health.get("status"),
        )
        shards.append(member)

    slo = None
    if args.slo == "on":
        slo = _build_slo_watchdog(args, metrics)
    router = ClusterRouter(
        {sh.name: sh.url for sh in shards},
        pairs,
        steal_threshold=args.steal_threshold,
        steal_latency_unit_s=args.steal_latency_unit_s,
        deadline_floor_ms=args.deadline_floor_ms,
        replication_factor=args.replication_factor,
        cut_through=(args.cut_through == "on"),
        metrics=metrics,
        scrape_interval_s=args.scrape_interval_s,
        scrape_timeout_s=args.scrape_timeout_s,
        slo=slo,
        tenant_top_k=args.tenant_top_k,
        # QoS lives at the front door only: a router-admitted request
        # must never 429 mid-scatter, so shards run unthrottled
        tenant_rate=args.tenant_rate,
        tenant_burst=args.tenant_burst,
    )
    httpd = RouterHTTPServer(router, host=args.host, port=args.port)
    httpd.start()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(str(httpd.port))
        os.replace(tmp, args.port_file)
    log.info(
        "cluster router on %s (%d shards, steal_threshold=%d, pairs=%d)",
        httpd.address, len(shards), args.steal_threshold, len(pairs),
    )
    if args.replication_factor > 1:
        # seed the replica tier now — every owner's segments mirror onto
        # its ring successors before the first corrupt frame needs them
        summary = router.replicate_now()
        log.info(
            "replication pass: R=%d, %d under-replicated arc(s), "
            "lag=%d segment(s)",
            args.replication_factor,
            len(summary.get("under_replicated") or ()),
            summary.get("lag_segments", 0),
        )

    def _sigterm(_signum, _frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        log.info("cluster draining (router first, then shards)…")
    finally:
        httpd.shutdown()
        for sh in shards:
            sh.stop()
        if tracing:
            _finish_tracing(args)
    log.info("cluster down; router metrics:\n%s", json.dumps(metrics.snapshot()))
    return 0


def speculate_depth_arg(value):
    # "auto" → adaptive backoff (FetchPlane lowers the depth when the
    # speculation waste ratio spikes); anything else must parse as int
    if value == "auto":
        return value
    try:
        return int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected an integer or 'auto', got {value!r}"
        )


def main(argv=None) -> int:
    from ipc_proofs_tpu.obs import install_crash_dump

    install_crash_dump()  # unhandled errors dump the flight recorder
    parser = argparse.ArgumentParser(prog="ipc-proofs-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    def add_failover_flags(p):
        p.add_argument(
            "--endpoints", action="append", default=None, metavar="URL",
            help="additional Lotus endpoint replicas (repeatable) — enables "
            "the failover pool: circuit breakers, health-scored routing, "
            "hedged fetches, per-endpoint integrity demotion",
        )
        p.add_argument(
            "--hedge-ms", type=float, default=None,
            help="hedged block fetches: fire a second fetch on the next "
            "healthy endpoint after this many ms (floor; the observed p99 "
            "raises it). Default: hedging off",
        )
        p.add_argument(
            "--breaker-threshold", type=int, default=5,
            help="consecutive failures that open an endpoint's circuit "
            "breaker (default 5)",
        )

    def add_store_flags(p):
        p.add_argument(
            "--store-dir", default=None, metavar="DIR",
            help="disk tier for fetched blocks: content-addressed "
            "append-only segment files under DIR, CRC-framed and "
            "multihash-verified on every read, LRU-evicted at "
            "--store-cap-bytes. Survives restarts — a re-run over the same "
            "heights refetches nothing",
        )
        p.add_argument(
            "--store-cap-bytes", type=int, default=1 << 30,
            help="byte cap on the disk tier (whole cold segments are "
            "evicted; default 1 GiB)",
        )
        p.add_argument(
            "--store-segment-max-bytes", type=int, default=64 << 20,
            help="roll the active segment at this size (default 64 MiB). "
            "Replication pulls skip the active tail, so replicated "
            "clusters want this small enough that hot data rolls promptly",
        )

    def add_fetch_plane_flags(p):
        p.add_argument(
            "--batch-rpc", action=argparse.BooleanOptionalAction, default=True,
            help="async fetch plane: ship block wants as JSON-RPC batch "
            "arrays (one round-trip per wave) and let HAMT/AMT walkers "
            "prefetch child links speculatively; endpoints that reject "
            "batch framing fall back to sequential calls automatically. "
            "--no-batch-rpc restores the one-call-per-block path",
        )
        p.add_argument(
            "--speculate-depth", type=speculate_depth_arg, default=1,
            metavar="N|auto",
            help="how many link levels the fetch plane chases below a "
            "decoded HAMT/AMT interior node (0 = batch demand fetches "
            "only, no speculation; default 1). 'auto' starts at 2 and "
            "backs off one level whenever a 64-fetch speculation window "
            "wastes more than 60%% of what it fetched "
            "(fetch.speculate_depth_downshifts counts the backoffs)",
        )

    def add_subs_flags(p):
        p.add_argument(
            "--subs-dir", default=None, metavar="DIR",
            help="standing queries: durable subscription registry + "
            "delivery log under DIR (IPJ1 journals — registrations and "
            "unacked deliveries survive restart). Mounts /v1/subscribe, "
            "/v1/unsubscribe, /v1/subscriptions and the long-poll "
            "/v1/deliveries; with --follow, each finalized tipset "
            "generates once per distinct filter and fans out to every "
            "subscriber (webhook push or long-poll)",
        )
        p.add_argument(
            "--push-max-inflight", type=int, default=4, metavar="N",
            help="webhook push worker threads (bounded fan-out; default 4)",
        )
        p.add_argument(
            "--delivery-retry-attempts", type=int, default=4, metavar="N",
            help="webhook attempts per delivery before leaving it unacked "
            "for long-poll / next-cycle re-push (default 4)",
        )
        p.add_argument(
            "--delivery-retry-base-s", type=float, default=0.25,
            help="full-jitter backoff base delay between webhook attempts "
            "(default 0.25)",
        )
        p.add_argument(
            "--delivery-retry-max-s", type=float, default=4.0,
            help="full-jitter backoff delay cap (default 4.0)",
        )
        p.add_argument(
            "--subs-log-cap-bytes", type=int, default=64 * 1024 * 1024,
            help="compact the delivery journal above this size — only "
            "acked history is dropped, unacked deliveries always survive "
            "(default 64 MiB)",
        )

    def add_witness_flags(p):
        p.add_argument(
            "--witness-delta", choices=["on", "off"], default="on",
            help="delta witnesses: honor If-Witness-Base / base_digest on "
            "requests (ship only blocks the client's base bundle lacks) "
            "and cut standing-query deliveries against each subscriber's "
            "acked base. Base mismatches fall back to full bundles "
            "(witness.delta_fallbacks) — never a wrong delta (default on)",
        )
        p.add_argument(
            "--witness-compress", choices=["on", "off"], default="on",
            help="compressed witness framing: honor witness_encoding / "
            "Accept-Witness-Encoding zlib (and zstd when importable) — "
            "canonical-order block frame + uncompressed digest; 'off' "
            "rejects compressed encodings with a typed 400 (default on)",
        )
        p.add_argument(
            "--witness-agg-max", type=int, default=1024, metavar="K",
            help="cap on claims per aggregated generate_range request "
            "(aggregate: true) — one merged witness + per-claim span "
            "table; beyond K the request gets a typed 400 (default 1024)",
        )
        p.add_argument(
            "--witness-base-cache", type=int, default=64, metavar="N",
            help="server-side LRU of witness base digests → CID sets used "
            "to answer delta requests (default 64 bases)",
        )

    def add_registry_flags(p):
        p.add_argument(
            "--registry-dir", default=None, metavar="DIR",
            help="proof provenance registry: seal every served bundle "
            "into a hash-linked IPR1 audit log (reg-<owner>.log) under "
            "DIR, mount GET /v1/registry/{head,entry,proof,consistency}, "
            "and use DIR as the fleet-wide delta base directory (shards "
            "sharing DIR see each other's serve records). Appends are "
            "fail-soft: registry trouble degrades /healthz, never serving",
        )
        p.add_argument(
            "--registry-owner", default="main", metavar="TOKEN",
            help="writer token naming this process's registry log file "
            "(each process sharing --registry-dir needs its own; default "
            "main)",
        )
        p.add_argument(
            "--registry-fsync", choices=["on", "off"], default="off",
            help="fsync each registry frame (durable audit contract) "
            "instead of riding the page cache; 'off' keeps append "
            "overhead under the 1%% serve-wall budget (default off)",
        )
        p.add_argument(
            "--subs-fleet", default="default", metavar="NAME",
            help="subscriber-fleet label for registry base records: acked "
            "delta bases are keyed (fleet, filter key) so any shard can "
            "find the newest base the whole fleet acked (default default)",
        )

    def add_onchip_flags(p):
        p.add_argument(
            "--mesh-devices", type=int, default=None, metavar="N",
            help="shard coalesced event-match batches across the first N "
            "local accelerator devices via pjit/NamedSharding (0 = all "
            "devices). Requires --backend tpu; results are bit-identical "
            "to the single-device path",
        )
        p.add_argument(
            "--batch-verify", action="store_true",
            help="verify chunk-granular read paths (fetch-plane landings, "
            "disk-tier reads, follower prefetch) with the device-batched "
            "multihash plane (ops.verify_jax) instead of per-block host "
            "hashing; verdicts are identical, small batches stay on the "
            "host (IPC_VERIFY_MIN_BYTES crossover)",
        )

    def add_trace_export_flags(p):
        p.add_argument(
            "--trace-otlp", default=None, metavar="PATH",
            help="also export collected spans as OTLP/JSON "
            "(resourceSpans/scopeSpans shape — POST to any OpenTelemetry "
            "collector's /v1/traces)",
        )
        p.add_argument(
            "--trace-otlp-url", default=None, metavar="URL",
            help="POST collected spans as OTLP/JSON to a live collector "
            "endpoint (e.g. http://localhost:4318/v1/traces); retried with "
            "bounded exponential backoff, fail-soft — a dead collector "
            "costs a warning and a trace.otlp_post_failures tick, never "
            "the run",
        )
        p.add_argument(
            "--trace-sample", type=float, default=1.0, metavar="RATE",
            help="head-sample collected traces at this rate in [0,1] "
            "(decided once per trace from its id, so exported trees are "
            "never torn; the always-on flight recorder ignores sampling). "
            "Default 1.0",
        )

    def add_fleet_obs_flags(p):
        p.add_argument(
            "--slo", default="off", choices=["on", "off"],
            help="run the SLO burn-rate watchdog: multi-window "
            "(fast/slow) burn rates per declarative target, an 'slo' "
            "block in /healthz, WARN records into the flight ring, and "
            "anomaly signatures (breaker flap storms, eviction storms, "
            "speculation-waste spikes). Default off",
        )
        p.add_argument(
            "--slo-availability", type=float, default=0.999,
            help="availability objective (fraction of requests that must "
            "not be rejected/failed; default 0.999)",
        )
        p.add_argument(
            "--slo-generate-p99-ms", type=float, default=2000.0,
            help="generate latency target: p99 must stay under this "
            "(default 2000)",
        )
        p.add_argument(
            "--slo-delivery-lag-p99-ms", type=float, default=5000.0,
            help="standing-query delivery lag target: p99 append→ack lag "
            "must stay under this (default 5000)",
        )
        p.add_argument(
            "--slo-interval-s", type=float, default=5.0,
            help="watchdog evaluation interval (default 5)",
        )
        p.add_argument(
            "--slo-fast-window-s", type=float, default=300.0,
            help="fast burn-rate window (default 300 = 5 min)",
        )
        p.add_argument(
            "--slo-slow-window-s", type=float, default=3600.0,
            help="slow burn-rate window (default 3600 = 1 h)",
        )
        p.add_argument(
            "--tenant-top-k", type=int, default=8, metavar="K",
            help="track per-tenant request/byte counters for the first K "
            "distinct tenants; later tenants aggregate into the 'other' "
            "bucket (bounds metric cardinality; default 8)",
        )
        p.add_argument(
            "--tenant-rate", type=float, default=None, metavar="R",
            help="per-tenant QoS: admit at most R proof requests/second "
            "per tenant (token bucket; sustained excess gets a typed 429 "
            "with Retry-After). Also arms the batcher's weighted-fair "
            "tenant ordering. Default off (no throttling)",
        )
        p.add_argument(
            "--tenant-burst", type=float, default=None, metavar="B",
            help="token-bucket burst depth per tenant (default 2×R): "
            "short spikes up to B requests admit immediately, then the "
            "bucket refills at --tenant-rate",
        )
        p.add_argument(
            "--tenant-weight", action="append", default=None,
            metavar="NAME=N",
            help="deficit weight for one tenant in the batcher's fair "
            "interactive lane (repeatable): a weight-N tenant drains up "
            "to N queued requests per round-robin turn; unlisted tenants "
            "weigh 1. In cluster mode the weights forward to every shard",
        )
        p.add_argument(
            "--admit-gradient", action="store_true",
            help="adaptive admission: replace the static queue bound as "
            "the effective concurrency gate with an AIMD limit driven by "
            "observed queue delay (grows +1 while p99 delay is well under "
            "budget, shrinks ×0.8 past it). Overload sheds with a typed "
            "429 + honest Retry-After from the drain estimate; unnamed "
            "('other') tenants shed before --tenant-weight tenants. "
            "Default off (static --queue-capacity only)",
        )
        p.add_argument(
            "--admit-delay-budget-ms", type=float, default=250.0,
            metavar="MS",
            help="queue-delay p99 budget steering --admit-gradient "
            "(default 250)",
        )
        p.add_argument(
            "--deadline-floor-ms", type=float, default=5.0, metavar="MS",
            help="deadline propagation floor: a request whose remaining "
            "budget (X-IPC-Deadline-Ms header / deadline_ms body field) "
            "is at/below this refuses typed (504, error_type=deadline) at "
            "each hop instead of burning a worker on an answer nobody "
            "can use (default 5)",
        )
        p.add_argument(
            "--retry-budget", type=float, default=None, metavar="R",
            help="pool-wide client retry budget in retries/second across "
            "ALL endpoints (token bucket, burst 2×R): during a broad "
            "outage retries stop amplifying load once the budget is dry "
            "(rpc.retry_budget_exhausted) and requests surface their "
            "error instead. Default off (per-request backoff only)",
        )

    gen = sub.add_parser("generate", help="generate a proof bundle from a live chain")
    gen.add_argument("--endpoint", required=True, help="Lotus JSON-RPC endpoint URL")
    gen.add_argument("--token", default=None, help="bearer token")
    gen.add_argument("--timeout", type=float, default=250.0)
    add_failover_flags(gen)
    gen.add_argument("--height", type=int, required=True, help="parent epoch H (child is H+1)")
    gen.add_argument("--contract", help="EVM contract address 0x…")
    gen.add_argument("--actor-id", type=int, default=None, help="skip address resolution")
    gen.add_argument("--slot-subnet", default=None, help="subnet id for mapping-slot proof")
    gen.add_argument("--slot-index", type=int, default=0)
    gen.add_argument("--event-sig", default=None, help='e.g. "NewTopDownMessage(bytes32,uint256)"')
    gen.add_argument("--topic1", default=None)
    gen.add_argument("--no-actor-filter", action="store_true")
    gen.add_argument("--backend", default="cpu", choices=["cpu", "tpu", "none"])
    gen.add_argument(
        "--receipts-api",
        action="store_true",
        help="enumerate pass-1 receipts via Filecoin.ChainGetParentReceipts "
        "(the reference's pathway) instead of walking the receipts AMT; "
        "needed for nodes that serve receipts only through the JSON API",
    )
    gen.add_argument("-o", "--output", default=None)
    gen.add_argument("--metrics", action="store_true")
    gen.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="export all request/stage/RPC spans as Chrome trace-event "
        "JSON (open at ui.perfetto.dev)",
    )
    add_trace_export_flags(gen)
    gen.set_defaults(fn=_cmd_generate)

    ver = sub.add_parser("verify", help="verify a saved bundle offline")
    ver.add_argument("bundle")
    ver.add_argument("--f3-cert", default=None, help="F3 finality certificate JSON")
    ver.add_argument("--event-sig", default=None)
    ver.add_argument("--topic1", default=None)
    ver.add_argument("--check-cids", action="store_true", help="recompute every witness CID")
    ver.set_defaults(fn=_cmd_verify)

    rng = sub.add_parser(
        "range", help="event (+ storage) proofs over an epoch range (chunked, resumable)"
    )
    rng.add_argument("--endpoint", required=True)
    rng.add_argument("--token", default=None)
    rng.add_argument("--timeout", type=float, default=250.0)
    add_failover_flags(rng)
    rng.add_argument("--from-height", type=int, required=True)
    rng.add_argument("--to-height", type=int, required=True)
    rng.add_argument("--contract", default=None)
    rng.add_argument("--event-sig", required=True)
    rng.add_argument("--topic1", required=True)
    rng.add_argument(
        "--storage-slot",
        action="append",
        default=None,
        metavar="KEY",
        help="also prove this mapping key's slot (of --contract) at every "
        "pair; repeatable — both proof kinds share the bundle witness",
    )
    rng.add_argument("--slot-index", type=int, default=0)
    rng.add_argument(
        "--scan-workers", type=int, default=8,
        help="thread-pool width for Phase-A scans over the RPC store "
        "(overlapping block fetches hides network latency; the reference "
        "fetches strictly one block at a time)",
    )
    rng.add_argument("--chunk-size", type=int, default=64)
    rng.add_argument(
        "--threads", type=int, default=None,
        help="ONE thread budget for the whole range engine: partitioned "
        "over scan/record/verify stage workers and the native scanner's "
        "per-call fan-out so the process never oversubscribes "
        "(flag > IPC_THREADS env > --scan-threads > IPC_SCAN_THREADS > "
        "CPU affinity; the resolved split is logged once)",
    )
    rng.add_argument(
        "--scan-threads", type=int, default=None,
        help="legacy: pin the scan+match stage worker count (also sets "
        "the whole budget when --threads/IPC_THREADS are absent)",
    )
    rng.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="chunks buffered between pipeline stages (bounded queues); "
        "0 disables the stage-overlapped engine",
    )
    add_store_flags(rng)
    add_fetch_plane_flags(rng)
    rng.add_argument("--checkpoint-dir", default=None)
    rng.add_argument(
        "--job-dir", default=None, metavar="DIR",
        help="write-ahead journal for crash-safe resume: every completed "
        "chunk is fsync'd to DIR/journal.bin; re-running with the same "
        "flags skips committed chunks (SIGKILL-safe — torn tail records "
        "are discarded)",
    )
    rng.add_argument(
        "--resume", action="store_true",
        help="require an existing job manifest in --job-dir (fail instead "
        "of silently starting a fresh job)",
    )
    rng.add_argument("--backend", default="cpu", choices=["cpu", "tpu", "none"])
    add_onchip_flags(rng)
    rng.add_argument("-o", "--output", default=None)
    rng.add_argument("--metrics", action="store_true")
    rng.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="emit a jax.profiler trace of generation into DIR "
        "(TensorBoard/Perfetto format)",
    )
    rng.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="export all request/stage/RPC spans as Chrome trace-event "
        "JSON (open at ui.perfetto.dev); unlike --profile this traces the "
        "whole run — scans, RPC retries, journal fsyncs — not just XLA",
    )
    add_trace_export_flags(rng)
    rng.set_defaults(fn=_cmd_range)

    bf = sub.add_parser(
        "backfill",
        help="prove deep history as a durable batch job: windowed, "
        "journal-resumable, streamed chunk by chunk",
    )
    bf.add_argument("--endpoint", default=None)
    bf.add_argument("--token", default=None)
    bf.add_argument("--timeout", type=float, default=250.0)
    add_failover_flags(bf)
    bf.add_argument("--from-height", type=int, default=None)
    bf.add_argument("--to-height", type=int, default=None)
    bf.add_argument("--contract", default=None)
    bf.add_argument("--event-sig", default=None)
    bf.add_argument("--topic1", default=None)
    bf.add_argument(
        "--demo-world", type=int, default=0, metavar="N_PAIRS",
        help="hermetic synthetic range world with N tipset pairs instead "
        "of a live endpoint (the batch analogue of `serve --demo-world`)",
    )
    bf.add_argument(
        "--demo-receipts", type=int, default=16, metavar="N",
        help="receipts per pair in the --demo-world (default 16)",
    )
    bf.add_argument(
        "--demo-match-rate", type=float, default=0.01,
        help="fraction of demo-world events matching the spec (default 0.01)",
    )
    bf.add_argument(
        "--pair-start", type=int, default=0, metavar="I",
        help="first pair-table index to prove (default 0)",
    )
    bf.add_argument(
        "--pair-end", type=int, default=None, metavar="J",
        help="one past the last pair-table index (default: whole table)",
    )
    bf.add_argument(
        "--window-size", type=int, default=8, metavar="N",
        help="epochs per schedulable window — the journal's commit and "
        "the stream's chunk granularity (default 8)",
    )
    bf.add_argument(
        "--work-ahead", type=int, default=2, metavar="N",
        help="future windows whose tipset headers prime the fetch "
        "plane's speculative lanes when a window starts (default 2)",
    )
    bf.add_argument(
        "--window-parallelism", type=int, default=1, metavar="N",
        help="windows proving concurrently (default 1 — the whole job "
        "occupies a single lane)",
    )
    bf.add_argument(
        "--jobs-dir", default=None, metavar="DIR",
        help="durable job root: each job journals committed windows "
        "under DIR/<job-id>/ (IPJ1, fsync'd). Re-running the identical "
        "command resumes from the journal — a SIGKILL loses at most the "
        "in-flight windows. Without it the job is not resumable",
    )
    bf.add_argument(
        "--chunk-size", type=int, default=None, metavar="N",
        help="chunk size within one window's driver run (default: the "
        "whole window as one chunk)",
    )
    bf.add_argument("--backend", default="cpu", choices=["cpu", "tpu", "none"])
    add_onchip_flags(bf)
    add_store_flags(bf)
    add_fetch_plane_flags(bf)
    bf.add_argument("-o", "--output", default=None)
    bf.add_argument("--metrics", action="store_true")
    bf.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="export all stage/RPC spans as Chrome trace-event JSON",
    )
    add_trace_export_flags(bf)
    bf.set_defaults(fn=_cmd_backfill)

    vec = sub.add_parser(
        "vectors", help="capture live-chain byte-compat vectors to a fixtures JSON"
    )
    vec.add_argument("--endpoint", required=True)
    vec.add_argument("--token", default=None)
    vec.add_argument("--timeout", type=float, default=250.0)
    vec.add_argument("--height", type=int, required=True)
    vec.add_argument("-o", "--output", default=None)
    vec.set_defaults(fn=_cmd_vectors)

    cert = sub.add_parser(
        "cert",
        help="inspect/validate F3 finality certificates (Forest JSON or "
        "go-f3 certexchange CBOR; chain continuity, delta replay, table "
        "commitments, optional BLS verification)",
    )
    cert.add_argument("certificates", nargs="+", help="certificate files (JSON or CBOR)")
    cert.add_argument(
        "--power-table",
        default=None,
        help="initial power table JSON [{ParticipantID, Power, SigningKey, Pop?}, …] "
        "for the first certificate's instance (enables delta replay + commitments)",
    )
    cert.add_argument(
        "--verify-signatures",
        action="store_true",
        help="verify each certificate's aggregate BLS signature and >2/3 quorum "
        "(requires --power-table)",
    )
    cert.add_argument(
        "--network",
        default=None,
        help='gpbft network name in the signing payload (default "filecoin")',
    )
    cert.add_argument(
        "--emit-cbor",
        default=None,
        metavar="PATH",
        help="re-encode the (single) certificate in go-f3 certexchange CBOR",
    )
    cert.set_defaults(fn=_cmd_cert)

    demo = sub.add_parser("demo", help="hermetic end-to-end demo on a synthetic chain")
    demo.set_defaults(fn=_cmd_demo)

    srv = sub.add_parser(
        "serve",
        help="long-running proof service: micro-batched verify/generate "
        "over JSON-HTTP with backpressure, deadlines, and /metrics",
    )
    srv.add_argument("--host", default="127.0.0.1")
    srv.add_argument("--port", type=int, default=8411)
    srv.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="write the bound port to PATH once listening (atomic rename) — "
        "how a parent that spawned this daemon on --port 0 learns where it "
        "landed (the cluster subcommand uses this)",
    )
    srv.add_argument(
        "--demo-world", type=int, default=0, metavar="N_PAIRS",
        help="serve a hermetic synthetic range world with N tipset pairs "
        "(enables /v1/generate with zero egress)",
    )
    srv.add_argument(
        "--demo-receipts", type=int, default=16, metavar="N",
        help="receipts per pair in the --demo-world (default 16)",
    )
    srv.add_argument(
        "--demo-match-rate", type=float, default=0.01,
        help="fraction of demo-world events matching the spec (default 0.01)",
    )
    srv.add_argument("--endpoint", default=None, help="Lotus JSON-RPC endpoint URL")
    srv.add_argument("--token", default=None)
    srv.add_argument("--timeout", type=float, default=250.0)
    add_failover_flags(srv)
    srv.add_argument("--from-height", type=int, default=None)
    srv.add_argument("--to-height", type=int, default=None)
    srv.add_argument("--event-sig", default=None)
    srv.add_argument("--topic1", default=None)
    srv.add_argument("--f3-cert", default=None, help="F3 finality certificate JSON")
    srv.add_argument("--check-cids", action="store_true")
    srv.add_argument(
        "--max-batch", type=int, default=32,
        help="flush a micro-batch at this many requests",
    )
    srv.add_argument(
        "--max-wait-ms", type=float, default=4.0,
        help="…or when the oldest queued request has waited this long",
    )
    srv.add_argument(
        "--queue-capacity", type=int, default=256,
        help="bounded admission queue; beyond this requests get 503 + Retry-After",
    )
    srv.add_argument("--workers", type=int, default=2, help="batch-execution threads")
    srv.add_argument(
        "--cache-max-bytes", type=int, default=256 * 1024 * 1024,
        help="shared block-cache budget (LRU-evicting)",
    )
    srv.add_argument(
        "--cache-ttl-s", type=float, default=None,
        help="optional TTL on cached blocks",
    )
    srv.add_argument(
        "--threads", type=int, default=None,
        help="ONE thread budget for multi-pair generate batches "
        "(stage-overlapped range engine): partitioned over "
        "scan/record/verify workers + native scan fan-out "
        "(flag > IPC_THREADS > --scan-threads > IPC_SCAN_THREADS > "
        "CPU affinity)",
    )
    srv.add_argument(
        "--scan-threads", type=int, default=None,
        help="legacy: pin the scan+match stage worker count for "
        "multi-pair generate batches",
    )
    srv.add_argument(
        "--pipeline-depth", type=int, default=2,
        help="chunks buffered between range-pipeline stages",
    )
    add_store_flags(srv)
    add_fetch_plane_flags(srv)
    add_subs_flags(srv)
    add_witness_flags(srv)
    add_registry_flags(srv)
    srv.add_argument(
        "--backend", default="none", choices=["cpu", "tpu", "none"],
        help="batch backend for generate-range event matching (default "
        "none = pure-python matcher)",
    )
    add_onchip_flags(srv)
    srv.add_argument(
        "--store-owner", default=None, metavar="TOKEN",
        help="join a SHARED --store-dir under this owner token (cluster "
        "shards): this process appends only to its own seg-TOKEN.* "
        "segments, reads everyone's, and eviction coordinates through a "
        "directory flock. Omit for an exclusive single-writer store",
    )
    srv.add_argument(
        "--follow", action="store_true",
        help="chain-follow prefetch: a daemon thread tails finalized "
        "tipsets (ChainHead minus a finality lag) and pre-warms the "
        "tiered store with headers, receipts-AMT and state-HAMT spine "
        "blocks — requests about recent tipsets then complete with zero "
        "upstream block fetches (requires --endpoint; best with "
        "--store-dir)",
    )
    srv.add_argument(
        "--follow-poll-s", type=float, default=15.0,
        help="chain-follower poll interval in seconds (default 15)",
    )
    srv.add_argument(
        "--backfill-jobs-dir", default=None, metavar="DIR",
        help="mount /v1/backfill: durable deep-history batch jobs, "
        "journaled under DIR (IPJ1, one subdirectory per deterministic "
        "job id — SIGKILL-resumable, identical re-submits dedup). "
        "Windows execute on the generate micro-batcher's LOW-priority "
        "lane, so a saturating backfill never starves interactive "
        "/v1/verify or /v1/generate; chunks stream incrementally via the "
        "long-poll cursor protocol (GET /v1/backfill/<id>/chunks"
        "?cursor=N). Needs a generate-capable service (--demo-world or "
        "--endpoint)",
    )
    srv.add_argument(
        "--backfill-window-size", type=int, default=8, metavar="N",
        help="epochs (tipset pairs) per backfill window — the journal "
        "commit and streaming granularity (default 8)",
    )
    srv.add_argument(
        "--queue-dir", default=None, metavar="DIR",
        help="durable admission queue: requests are journaled (fsync) to "
        "DIR/queue.bin before execution, idempotency_key dedupes client "
        "retries, and admitted-but-unfinished requests re-execute on "
        "restart (/healthz reports resumed_jobs / journal_bytes)",
    )
    srv.add_argument(
        "--results-cache-bytes", type=int, default=64 * 1024 * 1024,
        help="byte cap on the in-memory completed-request result cache "
        "(with --queue-dir): colder results spill to their journal frame "
        "and are re-read (CRC-verified) on an idempotent retry "
        "(default 64 MiB)",
    )
    srv.add_argument(
        "--slow-ms", type=float, default=1000.0,
        help="log a WARNING with the request's full span tree when a "
        "request takes longer than this end-to-end (default 1000)",
    )
    srv.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="export every request's spans as Chrome trace-event JSON on "
        "shutdown (open at ui.perfetto.dev)",
    )
    add_trace_export_flags(srv)
    add_fleet_obs_flags(srv)
    srv.set_defaults(fn=_cmd_serve)

    clu = sub.add_parser(
        "cluster",
        help="sharded serve plane: N serve shard processes behind a "
        "consistent-hash scatter-gather router (single-daemon wire "
        "protocol at the front door)",
    )
    clu.add_argument("--host", default="127.0.0.1")
    clu.add_argument("--port", type=int, default=8410)
    clu.add_argument(
        "--port-file", default=None, metavar="PATH",
        help="write the router's bound port to PATH once listening",
    )
    clu.add_argument(
        "--shards", type=int, default=4, metavar="N",
        help="serve shard processes to spawn (default 4)",
    )
    clu.add_argument(
        "--steal-threshold", type=int, default=4, metavar="D",
        help="steal a request from its affine shard when that shard's "
        "EFFECTIVE load (in-flight depth + latency penalty) exceeds the "
        "least-loaded shard's by D (affinity is a cache hint, never a "
        "correctness constraint; default 4)",
    )
    clu.add_argument(
        "--steal-latency-unit-s", type=float, default=0.25, metavar="S",
        help="latency-penalty unit for placement: a shard's observed "
        "dispatch EWMA counts as ewma/S phantom queue slots, so slow "
        "(cross-host) members lose steals they'd win on raw queue depth "
        "(default 0.25)",
    )
    clu.add_argument(
        "--shard-url", action="append", default=None, metavar="URL",
        help="admit an ALREADY-RUNNING serve daemon on another host as a "
        "cluster member (repeatable). The member must serve the same "
        "--demo-world pair table; it is health-probed before admission "
        "and failed over like a spawned shard if it stops answering",
    )
    clu.add_argument(
        "--replication-factor", type=int, default=1, metavar="R",
        help="replicate each shard's hot segment files onto the next R-1 "
        "distinct ring successors (R=1 disables). Arms peer-first "
        "read-repair of corrupt frames and re-replication after a host "
        "death. Shards need --store-dir to hold replicas (default 1)",
    )
    clu.add_argument(
        "--cut-through", default="on", choices=["on", "off"],
        help="relay shard stream chunks through the router as they "
        "arrive on streamed range responses, instead of buffering each "
        "shard's JSON sub-response (default on)",
    )
    clu.add_argument(
        "--demo-world", type=int, default=0, metavar="N_PAIRS",
        help="hermetic synthetic range world served by every shard "
        "(deterministic build → identical pair table in each; required)",
    )
    clu.add_argument(
        "--demo-receipts", type=int, default=16, metavar="N",
        help="receipts per pair in the demo world (default 16)",
    )
    clu.add_argument(
        "--demo-match-rate", type=float, default=0.01,
        help="fraction of demo-world events matching the spec (default 0.01)",
    )
    clu.add_argument("--event-sig", default=None)
    clu.add_argument("--topic1", default=None)
    add_store_flags(clu)
    add_subs_flags(clu)
    add_witness_flags(clu)
    add_registry_flags(clu)
    clu.add_argument(
        "--queue-dir", default=None, metavar="DIR",
        help="durable admission root: each shard journals under DIR/s<k> "
        "(crash recovery + idempotency dedup per shard — what makes the "
        "router's at-least-once failover retries safe)",
    )
    clu.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="export router spans as Chrome trace-event JSON on shutdown",
    )
    add_trace_export_flags(clu)
    add_fleet_obs_flags(clu)
    clu.add_argument(
        "--scrape-interval-s", type=float, default=5.0,
        help="fleet federation: router background-scrape interval for "
        "each shard's /metrics.json + /healthz (default 5)",
    )
    clu.add_argument(
        "--scrape-timeout-s", type=float, default=2.0,
        help="per-shard scrape timeout; a slow or dead shard drops out "
        "of the fleet view for that round instead of stalling it "
        "(default 2)",
    )
    clu.set_defaults(fn=_cmd_cluster)

    args = parser.parse_args(argv)
    if getattr(args, "event_sig", None) and not getattr(args, "topic1", None):
        parser.error("--event-sig requires --topic1")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
