"""ipc_proofs_tpu — TPU-native framework for IPC cross-chain proofs.

A from-scratch re-design of the capabilities of
consensus-shipyard/ipc-filecoin-proofs (Rust, single-threaded CPU) as a
batch-first, TPU-native framework:

- ``core``     — IPLD byte layer: canonical DAG-CBOR, CIDv1, varint,
                 keccak256 / blake2b-256 (replaces the reference's external
                 crates ``serde_ipld_dagcbor``/``cid``/``multihash``/``sha3``).
- ``store``    — the Blockstore plugin boundary (memory / recording / cached /
                 RPC), mirroring reference ``src/client/*blockstore.rs`` and
                 ``src/proofs/common/blockstore.rs``.
- ``ipld``     — AMT (v0 + v3) and HAMT readers *and writers* (the reference
                 delegates to ``fvm_ipld_amt``/``fvm_ipld_hamt`` and has no
                 writers; writers here enable hermetic fixtures).
- ``state``    — Filecoin state schema decode (headers, actors, EVM state,
                 events, receipts, addresses, storage-slot encodings).
- ``proofs``   — storage/event proof engines, unified bundle API, trust
                 policies (reference ``src/proofs/``).
- ``backend``  — the BatchHashBackend seam: CPU (numpy + C++ ext) and TPU
                 (JAX/Pallas) implementations of the batch inner loops.
- ``ops``      — JAX / Pallas kernels (keccak-f[1600], blake2b, match masks).
- ``parallel`` — device-mesh sharding helpers for the batch pipeline.
"""

__version__ = "0.1.0"

_LAZY = {
    "CID": ("ipc_proofs_tpu.core.cid", "CID"),
    "ProofBlock": ("ipc_proofs_tpu.proofs.bundle", "ProofBlock"),
    "UnifiedProofBundle": ("ipc_proofs_tpu.proofs.bundle", "UnifiedProofBundle"),
    "UnifiedVerificationResult": (
        "ipc_proofs_tpu.proofs.bundle",
        "UnifiedVerificationResult",
    ),
    "StorageProofSpec": ("ipc_proofs_tpu.proofs.generator", "StorageProofSpec"),
    "EventProofSpec": ("ipc_proofs_tpu.proofs.generator", "EventProofSpec"),
    "generate_proof_bundle": ("ipc_proofs_tpu.proofs.generator", "generate_proof_bundle"),
    "verify_proof_bundle": ("ipc_proofs_tpu.proofs.verifier", "verify_proof_bundle"),
    "TrustPolicy": ("ipc_proofs_tpu.proofs.trust", "TrustPolicy"),
    "TrustVerifier": ("ipc_proofs_tpu.proofs.trust", "TrustVerifier"),
    "MockTrustVerifier": ("ipc_proofs_tpu.proofs.trust", "MockTrustVerifier"),
}


def __getattr__(name):
    """Lazy re-exports so `import ipc_proofs_tpu.core` never pulls in JAX."""
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "CID",
    "ProofBlock",
    "UnifiedProofBundle",
    "UnifiedVerificationResult",
    "StorageProofSpec",
    "EventProofSpec",
    "generate_proof_bundle",
    "verify_proof_bundle",
    "TrustPolicy",
    "TrustVerifier",
    "MockTrustVerifier",
]
