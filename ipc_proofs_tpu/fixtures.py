"""Synthetic Filecoin chain fixtures: hermetic test/benchmark worlds.

The reference cannot be tested without a live Lotus node (SURVEY.md §4);
this module uses the framework's own AMT/HAMT/header *writers* to synthesize
a complete parent→child tipset pair in a MemoryBlockstore:

    state tree HAMT → EVM actor states → storage HAMTs
    TxMeta (bls/secp message AMTs v0) → receipts AMT v0 → events AMTs v3

so both proof engines can run end-to-end offline — and so benchmarks can
scale the world (tipsets × receipts × events) arbitrarily.

The contract/event semantics modeled here (slot-0 mapping keyed by subnet id,
pre-incremented nonce, ``NewTopDownMessage(bytes32,uint256)`` with the subnet
id as indexed topic1) are those of the deployable fixture at
``contracts/TopdownMessenger.sol`` (reference parity:
``topdown-messenger/src/TopdownMessenger.sol:1-33``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ipc_proofs_tpu.core.cid import CID, RAW
from ipc_proofs_tpu.ipld.amt import amt_build, amt_build_v0
from ipc_proofs_tpu.ipld.hamt import hamt_build
from ipc_proofs_tpu.proofs.chain import Tipset
from ipc_proofs_tpu.state.actors import ActorState, StateRoot
from ipc_proofs_tpu.state.address import Address
from ipc_proofs_tpu.state.events import (
    ActorEvent,
    EventEntry,
    IPLD_RAW,
    Receipt,
    StampedEvent,
    ascii_to_bytes32,
    hash_event_signature,
)
from ipc_proofs_tpu.state.header import BlockHeader
from ipc_proofs_tpu.store.blockstore import Blockstore, MemoryBlockstore, put_cbor

__all__ = [
    "ContractFixture",
    "EventFixture",
    "ChainFixture",
    "build_chain",
    "build_range_world",
]


@dataclass
class ContractFixture:
    """An EVM contract actor with a storage map (slot digest → raw value)."""

    actor_id: int
    storage: dict[bytes, bytes] = field(default_factory=dict)
    nonce: int = 1
    storage_encoding: str = "direct"  # direct | wrapper_tuple | wrapper_map | inline


@dataclass
class EventFixture:
    """One EVM event emitted by a message."""

    emitter: int
    signature: str
    topic1: str
    extra_topics: list[bytes] = field(default_factory=list)
    data: bytes = b"\x00" * 32
    encoding: str = "compact"  # compact (t1..t4 + d) | concat (topics + data)

    def to_stamped(self) -> StampedEvent:
        topics = [
            hash_event_signature(self.signature),
            ascii_to_bytes32(self.topic1),
            *self.extra_topics,
        ]
        if self.encoding == "compact":
            entries = [
                EventEntry(0, f"t{i + 1}", IPLD_RAW, t) for i, t in enumerate(topics[:4])
            ]
            entries.append(EventEntry(0, "d", IPLD_RAW, self.data))
        elif self.encoding == "concat":
            entries = [
                EventEntry(0, "topics", IPLD_RAW, b"".join(topics)),
                EventEntry(0, "data", IPLD_RAW, self.data),
            ]
        else:
            raise ValueError(f"unknown event encoding {self.encoding}")
        return StampedEvent(emitter=self.emitter, event=ActorEvent(entries=entries))


@dataclass
class ChainFixture:
    store: MemoryBlockstore
    parent: Tipset
    child: Tipset
    state_root_cid: CID
    receipts_root: CID
    message_cids: list[CID]  # canonical execution order
    contracts: dict[int, ContractFixture]


def _storage_root(store: Blockstore, contract: ContractFixture) -> CID:
    """Write the contract's storage in the requested on-disk encoding
    (the five cases of reference `storage/decode.rs:36-97`)."""
    if contract.storage_encoding == "direct":
        return hamt_build(store, dict(contract.storage))
    if contract.storage_encoding == "wrapper_tuple":
        inner = hamt_build(store, dict(contract.storage))
        return put_cbor(store, [inner, 5])
    if contract.storage_encoding == "wrapper_map":
        inner = hamt_build(store, dict(contract.storage))
        return put_cbor(store, {"root": inner, "bitwidth": 5})
    if contract.storage_encoding == "inline":
        small_map = {"v": [[k, v] for k, v in sorted(contract.storage.items())]}
        return put_cbor(store, [b"params", small_map])
    raise ValueError(f"unknown storage encoding {contract.storage_encoding}")


def build_chain(
    contracts: list[ContractFixture],
    events_per_message: list[list[EventFixture]],
    parent_height: int = 100,
    n_parent_blocks: int = 1,
    n_filler_actors: int = 50,
    store: Optional[MemoryBlockstore] = None,
    failed_message_indices: Optional[set[int]] = None,
) -> ChainFixture:
    """Build a full synthetic parent(H) → child(H+1) world.

    ``events_per_message[i]`` lists the events emitted by message i (in
    canonical execution order). Messages are spread round-robin across
    ``n_parent_blocks`` parent blocks, alternating BLS/secp lists.
    """
    bs = store if store is not None else MemoryBlockstore()
    failed = failed_message_indices or set()

    # --- state tree ---------------------------------------------------------
    actors: dict[bytes, list] = {}
    for contract in contracts:
        storage_root = _storage_root(bs, contract)
        bytecode_cid = CID.hash_of(f"bytecode-{contract.actor_id}".encode(), codec=RAW)
        evm_state_cid = put_cbor(
            bs,
            [bytecode_cid, b"\xbc" * 32, storage_root, None, contract.nonce, None],
        )
        actor = ActorState(
            code=CID.hash_of(b"fil/evm", codec=RAW),
            state=evm_state_cid,
            call_seq_num=contract.nonce,
            balance=0,
        )
        actors[Address.new_id(contract.actor_id).to_bytes()] = actor.to_tuple()

    for i in range(n_filler_actors):
        filler_state = put_cbor(bs, [i, f"filler-{i}"])
        actor = ActorState(
            code=CID.hash_of(b"fil/account", codec=RAW),
            state=filler_state,
            call_seq_num=0,
            balance=i,
        )
        actors[Address.new_id(10_000 + i).to_bytes()] = actor.to_tuple()

    actors_root = hamt_build(bs, actors)
    info_cid = put_cbor(bs, "state-info")
    state_root_cid = put_cbor(bs, StateRoot(version=5, actors=actors_root, info=info_cid).to_tuple())

    # --- messages: round-robin across parent blocks, BLS evens / secp odds --
    n_messages = len(events_per_message)
    message_cids = [
        CID.hash_of(f"message-{i}".encode(), codec=RAW) for i in range(n_messages)
    ]
    per_block_bls: list[dict[int, CID]] = [dict() for _ in range(n_parent_blocks)]
    per_block_secp: list[dict[int, CID]] = [dict() for _ in range(n_parent_blocks)]
    # Canonical execution order is: per block (tipset order), BLS list then
    # secp list. Assign contiguous chunks per block, first half BLS / second
    # half secp, so canonical order == message_cids order and
    # ``events_per_message[i]`` means "the i-th executed message".
    chunk = (n_messages + n_parent_blocks - 1) // max(n_parent_blocks, 1)
    for block in range(n_parent_blocks):
        block_msgs = message_cids[block * chunk : (block + 1) * chunk]
        split = (len(block_msgs) + 1) // 2
        for cid in block_msgs[:split]:
            per_block_bls[block][len(per_block_bls[block])] = cid
        for cid in block_msgs[split:]:
            per_block_secp[block][len(per_block_secp[block])] = cid

    txmeta_cids = []
    for block in range(n_parent_blocks):
        bls_root = amt_build_v0(bs, per_block_bls[block])
        secp_root = amt_build_v0(bs, per_block_secp[block])
        txmeta_cids.append(put_cbor(bs, [bls_root, secp_root]))

    # canonical execution order: per block, BLS then secp, first-seen dedup
    exec_order: list[CID] = []
    seen: set[CID] = set()
    for block in range(n_parent_blocks):
        for group in (per_block_bls[block], per_block_secp[block]):
            for _, cid in sorted(group.items()):
                if cid not in seen:
                    seen.add(cid)
                    exec_order.append(cid)

    # --- receipts + events (indexed by canonical execution position) --------
    events_by_cid = {message_cids[i]: events_per_message[i] for i in range(n_messages)}
    failed_cids = {message_cids[i] for i in failed}
    receipts: list[list] = []
    for position, msg_cid in enumerate(exec_order):
        events = events_by_cid[msg_cid]
        events_root = None
        if events and msg_cid not in failed_cids:
            stamped = [e.to_stamped().to_cbor() for e in events]
            events_root = amt_build(bs, stamped, bit_width=5, version=3)
        receipt = Receipt(
            exit_code=1 if msg_cid in failed_cids else 0,
            return_data=b"",
            gas_used=1_000_000 + position,
            events_root=events_root,
        )
        receipts.append(receipt.to_cbor())
    receipts_root = amt_build_v0(bs, receipts)

    # --- headers ------------------------------------------------------------
    grandparent_cids = [CID.hash_of(b"grandparent-block", codec=RAW)]
    old_state = put_cbor(bs, StateRoot(version=5, actors=hamt_build(bs, {}), info=info_cid).to_tuple())
    empty_amt = amt_build_v0(bs, [])
    old_receipts = amt_build_v0(bs, [])

    parent_headers = []
    for block in range(n_parent_blocks):
        parent_headers.append(
            BlockHeader(
                parents=grandparent_cids,
                height=parent_height,
                parent_state_root=old_state,
                parent_message_receipts=old_receipts,
                messages=txmeta_cids[block],
                timestamp=1_700_000_000 + parent_height * 30,
                miner=f"f0{1000 + block}",
            )
        )
    parent_cids = []
    for header in parent_headers:
        raw = header.encode()
        cid = CID.hash_of(raw)
        bs.put_keyed(cid, raw)
        parent_cids.append(cid)
    parent = Tipset(cids=parent_cids, blocks=parent_headers, height=parent_height)

    child_txmeta = put_cbor(bs, [empty_amt, empty_amt])
    child_header = BlockHeader(
        parents=parent_cids,
        height=parent_height + 1,
        parent_state_root=state_root_cid,
        parent_message_receipts=receipts_root,
        messages=child_txmeta,
        timestamp=1_700_000_000 + (parent_height + 1) * 30,
        miner="f02000",
    )
    child_raw = child_header.encode()
    child_cid = CID.hash_of(child_raw)
    bs.put_keyed(child_cid, child_raw)
    child = Tipset(cids=[child_cid], blocks=[child_header], height=parent_height + 1)

    return ChainFixture(
        store=bs,
        parent=parent,
        child=child,
        state_root_cid=state_root_cid,
        receipts_root=receipts_root,
        message_cids=exec_order,
        contracts={c.actor_id: c for c in contracts},
    )


def build_range_world(
    n_pairs: int,
    receipts_per_pair: int = 16,
    events_per_receipt: int = 4,
    match_rate: float = 0.01,
    signature: str = "NewTopDownMessage(bytes32,uint256)",
    topic1: str = "calib-subnet-1",
    actor_id: int = 1001,
    base_height: int = 1000,
    store: Optional[MemoryBlockstore] = None,
):
    """A benchmark-scale range of parent→child pairs sharing one state tree.

    ``build_chain`` rebuilds the full state tree per call (fine for tests,
    ~ms each); a 4096-pair north-star range needs the cheap path: the state
    tree is written once, and each pair gets only its own messages, receipts,
    events AMTs, and headers. Event payloads embed (pair, receipt, event)
    indices so blocks are unique across the range — no artificial CID dedup
    shrinking the scan or witness workload.

    A fraction ``match_rate`` of receipts (evenly spread) contain exactly one
    event matching ``(signature, topic1, actor_id)``; all other events are
    noise with a different signature. Returns ``(store, pairs,
    n_matching_receipts)`` where ``pairs`` is a list of objects with
    ``parent`` / ``child`` attributes (duck-compatible with
    `proofs.range.TipsetPair`).
    """
    from ipc_proofs_tpu.proofs.range import TipsetPair

    bs = store if store is not None else MemoryBlockstore()

    # --- shared state tree (one contract actor, written once) ---------------
    storage_root = hamt_build(bs, {})
    bytecode_cid = CID.hash_of(b"range-bytecode", codec=RAW)
    evm_state_cid = put_cbor(bs, [bytecode_cid, b"\xbc" * 32, storage_root, None, 1, None])
    actor = ActorState(
        code=CID.hash_of(b"fil/evm", codec=RAW), state=evm_state_cid,
        call_seq_num=1, balance=0,
    )
    actors_root = hamt_build(bs, {Address.new_id(actor_id).to_bytes(): actor.to_tuple()})
    info_cid = put_cbor(bs, "state-info")
    state_root_cid = put_cbor(bs, StateRoot(version=5, actors=actors_root, info=info_cid).to_tuple())
    grandparent_cids = [CID.hash_of(b"range-grandparent", codec=RAW)]
    old_receipts = amt_build_v0(bs, [])
    empty_amt = amt_build_v0(bs, [])
    child_txmeta = put_cbor(bs, [empty_amt, empty_amt])

    # pre-encoded topics shared by every event
    topic0 = hash_event_signature(signature)
    t1 = ascii_to_bytes32(topic1)
    noise_topic0 = hash_event_signature("Noise(uint256)")

    every = max(int(round(1.0 / match_rate)), 1) if match_rate > 0 else 0
    n_matching = 0
    pairs = []
    for p in range(n_pairs):
        receipts = []
        msg_cids = []
        for r in range(receipts_per_pair):
            gid = p * receipts_per_pair + r
            msg_cids.append(CID.hash_of(b"msg-%d" % gid, codec=RAW))
            stamped = []
            for e in range(events_per_receipt):
                uniq = (gid * events_per_receipt + e).to_bytes(32, "big")
                if every and gid % every == 0 and e == 0:
                    entries = [[0, "t1", IPLD_RAW, topic0], [0, "t2", IPLD_RAW, t1],
                               [0, "d", IPLD_RAW, uniq]]
                else:
                    entries = [[0, "t1", IPLD_RAW, noise_topic0], [0, "t2", IPLD_RAW, uniq],
                               [0, "d", IPLD_RAW, uniq]]
                stamped.append([actor_id, entries])
            if every and gid % every == 0:
                n_matching += 1
            events_root = amt_build(bs, stamped, bit_width=5, version=3)
            receipts.append([0, b"", 1_000_000 + gid, events_root])
        receipts_root = amt_build_v0(bs, receipts)
        bls_root = amt_build_v0(bs, {i: c for i, c in enumerate(msg_cids)})
        txmeta = put_cbor(bs, [bls_root, empty_amt])

        height = base_height + 2 * p
        parent_header = BlockHeader(
            parents=grandparent_cids, height=height,
            parent_state_root=state_root_cid, parent_message_receipts=old_receipts,
            messages=txmeta, timestamp=1_700_000_000 + height * 30, miner="f01000",
        )
        parent_raw = parent_header.encode()
        parent_cid = CID.hash_of(parent_raw)
        bs.put_keyed(parent_cid, parent_raw)
        child_header = BlockHeader(
            parents=[parent_cid], height=height + 1,
            parent_state_root=state_root_cid, parent_message_receipts=receipts_root,
            messages=child_txmeta, timestamp=1_700_000_000 + (height + 1) * 30, miner="f02000",
        )
        child_raw = child_header.encode()
        child_cid = CID.hash_of(child_raw)
        bs.put_keyed(child_cid, child_raw)
        pairs.append(
            TipsetPair(
                parent=Tipset(cids=[parent_cid], blocks=[parent_header], height=height),
                child=Tipset(cids=[child_cid], blocks=[child_header], height=height + 1),
            )
        )
    return bs, pairs, n_matching
