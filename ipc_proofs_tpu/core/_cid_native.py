"""Build-and-load for the C DAG-CBOR/CID extension, import-cycle-free.

This lives in ``core`` (stdlib-only imports) so :mod:`core.cid` can bind
the native CID type at module import without pulling in the backend
package — whose ``__init__`` transitively imports half the tree and would
capture the pure-Python CID mid-rebind (modules imported during the load
would hold a stale class). :mod:`ipc_proofs_tpu.backend.native` delegates
here so there is exactly one build cache and one loaded module.

Deliberate tradeoff: binding at import means a COLD checkout pays the gcc
compile (~2-5 s, once per host) on the first ``import ipc_proofs_tpu``
even for commands that never decode. The alternative — deferring the
build to first decode — reintroduces the stale-class hazard this module
exists to kill (every module imported before the rebind would hold the
pure-Python CID). Warm checkouts load the cached .so instantly.
"""

from __future__ import annotations

import os
import subprocess
import threading
from ipc_proofs_tpu.utils.lockdep import named_lock
from pathlib import Path

__all__ = ["load", "build_cpython_ext", "host_build_id", "BUILD_DIR", "SAN_FLAGS"]

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "backend" / "native"
BUILD_DIR = _NATIVE_DIR / "build"
_DAGCBOR_SRC = _NATIVE_DIR / "dagcbor_ext.c"
_DAGCBOR_SO = BUILD_DIR / "ipc_dagcbor_ext.so"

_lock = named_lock("_cid_native._lock")
_cached: "object | None | bool" = False  # False = not attempted yet

# sanitizer build profile (tools/build_native_san.py sets IPC_PROOFS_SAN=1):
# ASan+UBSan with the warning set promoted to errors, frame pointers kept
# for usable reports
SAN_FLAGS = (
    "-fsanitize=address,undefined",
    "-fno-omit-frame-pointer",
    "-g",
    "-Wall",
    "-Wextra",
    "-Werror",
)


def host_build_id() -> str:
    """Identity of the CPU the cached .so was tuned for — a checkout (or
    container image) moved to a different host must rebuild rather than
    run a stale -march=native binary into SIGILL."""
    import hashlib
    import platform

    model = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    if not model:
        model = platform.processor() or "unknown"
    return hashlib.sha256(f"{platform.machine()}|{model}".encode()).hexdigest()[:16]


def build_cpython_ext(src: Path, so: Path, mod_name: str):
    """Compile (mtime- AND host-stamp-cached) and import a raw-CPython-API
    extension."""
    import importlib.util
    import sysconfig

    BUILD_DIR.mkdir(exist_ok=True)
    # sanitized builds live under distinct names (.san.so + own host stamp)
    # so they never collide with the fast-path cache of the same source
    sanitize = bool(os.environ.get("IPC_PROOFS_SAN"))
    if sanitize:
        so = so.with_name(so.name[: -len(so.suffix)] + ".san" + so.suffix)
    stamp = so.with_suffix(so.suffix + ".host")
    host_id = host_build_id()
    cached = (
        so.exists()
        and so.stat().st_mtime >= src.stat().st_mtime
        and stamp.exists()
        and stamp.read_text() == host_id
    )
    if not cached:
        include = sysconfig.get_paths()["include"]
        base = ["gcc", "-O3", "-shared", "-fPIC", "-pthread", f"-I{include}",
                str(src), "-o", str(so)]
        if sanitize:
            base[1:1] = list(SAN_FLAGS)
        try:
            # host-tuned codegen measurably helps the scan parse loop;
            # retry portable if the toolchain rejects -march=native
            subprocess.run(
                base[:2] + ["-march=native"] + base[2:],
                check=True, capture_output=True, timeout=120,
            )
        except subprocess.SubprocessError:
            subprocess.run(base, check=True, capture_output=True, timeout=120)
        stamp.write_text(host_id)
    spec = importlib.util.spec_from_file_location(mod_name, so)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def load():
    """Compile (if needed) and import the C DAG-CBOR/CID module, or None on
    any failure. Honors ``IPC_PROOFS_NO_NATIVE``."""
    global _cached
    with _lock:
        if _cached is not False:
            return _cached
        if os.environ.get("IPC_PROOFS_NO_NATIVE"):
            _cached = None
            return None
        try:
            _cached = build_cpython_ext(_DAGCBOR_SRC, _DAGCBOR_SO, "ipc_dagcbor_ext")  # ipclint: disable=lock-held-blocking (one-time toolchain build, serialized by design)
        except Exception:  # fail-soft: no toolchain → pure-Python CID/codec, bit-identical by contract
            _cached = None
        return _cached
