"""Canonical DAG-CBOR codec with tag-42 CID links.

Replaces the reference's ``serde_ipld_dagcbor`` / ``fvm_ipld_encoding``
(reference ``Cargo.toml:20-22``; used by every decode path, e.g.
``src/proofs/common/decode.rs`` and the TxMeta CID recompute at
``src/proofs/events/utils.rs:65``).

Canonical rules (RFC 8949 core deterministic encoding as profiled by DAG-CBOR):
- minimal-length integer heads everywhere;
- definite lengths only;
- map keys must be strings, sorted length-first then bytewise (RFC 7049
  canonical form, as used by go-ipld / canonical CBOR);
- CIDs encode as tag 42 wrapping a byte string of ``0x00 ++ cid-bytes``
  (the multibase identity prefix).

Python value mapping: int, bytes, str, bool, None, list/tuple, dict,
:class:`~ipc_proofs_tpu.core.cid.CID`, float (f64, decode-tolerant).
"""

from __future__ import annotations

import math
import struct
from contextlib import contextmanager as _contextmanager
from typing import Any

from ipc_proofs_tpu.core.cid import CID, CID_TYPES

__all__ = ["encode", "decode"]

_MAJOR_UINT = 0
_MAJOR_NEGINT = 1
_MAJOR_BYTES = 2
_MAJOR_TEXT = 3
_MAJOR_ARRAY = 4
_MAJOR_MAP = 5
_MAJOR_TAG = 6
_MAJOR_SIMPLE = 7

_CID_TAG = 42


def _encode_head(major: int, value: int) -> bytes:
    if value < 24:
        return bytes([(major << 5) | value])
    if value < 0x100:
        return bytes([(major << 5) | 24, value])
    if value < 0x10000:
        return bytes([(major << 5) | 25]) + value.to_bytes(2, "big")
    if value < 0x100000000:
        return bytes([(major << 5) | 26]) + value.to_bytes(4, "big")
    if value < 0x10000000000000000:
        return bytes([(major << 5) | 27]) + value.to_bytes(8, "big")
    raise ValueError("integer too large for CBOR head")


def _encode_item(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, CID_TYPES):  # either CID implementation
        out += _encode_head(_MAJOR_TAG, _CID_TAG)
        inner = b"\x00" + obj.to_bytes()
        out += _encode_head(_MAJOR_BYTES, len(inner))
        out += inner
    elif isinstance(obj, int):
        if obj >= 0:
            out += _encode_head(_MAJOR_UINT, obj)
        else:
            out += _encode_head(_MAJOR_NEGINT, -1 - obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        data = bytes(obj)
        out += _encode_head(_MAJOR_BYTES, len(data))
        out += data
    elif isinstance(obj, str):
        data = obj.encode("utf-8")
        out += _encode_head(_MAJOR_TEXT, len(data))
        out += data
    elif isinstance(obj, (list, tuple)):
        out += _encode_head(_MAJOR_ARRAY, len(obj))
        for item in obj:
            _encode_item(item, out)
    elif isinstance(obj, dict):
        out += _encode_head(_MAJOR_MAP, len(obj))
        entries = []
        for key, value in obj.items():
            if not isinstance(key, str):
                raise TypeError(f"DAG-CBOR map keys must be strings, got {type(key)}")
            entries.append((key.encode("utf-8"), value))
        entries.sort(key=lambda kv: (len(kv[0]), kv[0]))
        for key_bytes, value in entries:
            out += _encode_head(_MAJOR_TEXT, len(key_bytes))
            out += key_bytes
            _encode_item(value, out)
    elif isinstance(obj, float):
        if not math.isfinite(obj):
            raise ValueError("DAG-CBOR forbids non-finite floats")
        out.append(0xFB)
        out += struct.pack(">d", obj)
    else:
        raise TypeError(f"cannot encode {type(obj)} as DAG-CBOR")


def encode(obj: Any) -> bytes:
    out = bytearray()
    _encode_item(obj, out)
    return bytes(out)


def _decode_head(data: bytes, pos: int) -> tuple[int, int, int]:
    if pos >= len(data):
        raise ValueError("truncated CBOR head")
    byte = data[pos]
    major = byte >> 5
    info = byte & 0x1F
    pos += 1
    if info < 24:
        return major, info, pos
    if info > 27:
        raise ValueError(
            f"indefinite/reserved CBOR length (info={info}) not allowed in DAG-CBOR"
        )
    extra = 1 << (info - 24)
    if pos + extra > len(data):
        raise ValueError("truncated CBOR head")
    return major, int.from_bytes(data[pos : pos + extra], "big"), pos + extra


# Nesting cap shared with the C extension (MAX_CBOR_DEPTH): malicious
# deeply nested input must raise ValueError, not exhaust the stack.
_MAX_DEPTH = 512


def _decode_item(data: bytes, pos: int, depth: int = 0) -> tuple[Any, int]:
    # 0-based depth here vs the C extension's 1-based counter: >= aligns
    # both to error at exactly the same nesting level
    if depth >= _MAX_DEPTH:
        raise ValueError("CBOR nesting too deep")
    head_start = pos
    major, value, pos = _decode_head(data, pos)
    if major == _MAJOR_UINT:
        return value, pos
    if major == _MAJOR_NEGINT:
        return -1 - value, pos
    if major == _MAJOR_BYTES:
        end = pos + value
        if end > len(data):
            raise ValueError("truncated CBOR bytes")
        return bytes(data[pos:end]), end
    if major == _MAJOR_TEXT:
        end = pos + value
        if end > len(data):
            raise ValueError("truncated CBOR text")
        return data[pos:end].decode("utf-8"), end
    if major == _MAJOR_ARRAY:
        items = []
        for _ in range(value):
            item, pos = _decode_item(data, pos, depth + 1)
            items.append(item)
        return items, pos
    if major == _MAJOR_MAP:
        result: dict[str, Any] = {}
        for _ in range(value):
            key, pos = _decode_item(data, pos, depth + 1)
            if not isinstance(key, str):
                raise ValueError("DAG-CBOR map keys must be strings")
            val, pos = _decode_item(data, pos, depth + 1)
            result[key] = val
        return result, pos
    if major == _MAJOR_TAG:
        if value != _CID_TAG:
            raise ValueError(f"unsupported CBOR tag {value} (DAG-CBOR allows only 42)")
        inner, pos = _decode_item(data, pos, depth + 1)
        if not isinstance(inner, bytes) or not inner.startswith(b"\x00"):
            raise ValueError("tag-42 content must be identity-multibase CID bytes")
        return CID.from_bytes(inner[1:]), pos
    # simple values / floats (major 7): distinguish by the head's info bits
    info = data[head_start] & 0x1F
    if info == 27:  # f64 — `value` holds the raw 8-byte payload as an int
        return struct.unpack(">d", value.to_bytes(8, "big"))[0], pos
    if value == 20:
        return False, pos
    if value == 21:
        return True, pos
    if value == 22:
        return None, pos
    raise ValueError(f"unsupported CBOR simple value {value}")


def decode_py(data: bytes) -> Any:
    """The pure-Python decoder (correctness reference for the C extension)."""
    obj, pos = _decode_item(bytes(data), 0)
    if pos != len(data):
        raise ValueError(f"trailing bytes after CBOR item ({len(data) - pos} bytes)")
    return obj


_native = False  # False = not resolved yet; None = unavailable


def _resolve_native():
    global _native
    try:
        from ipc_proofs_tpu.backend.native import load_dagcbor_ext

        # load_dagcbor_ext registers the CID factory/class hooks itself —
        # that loader is the single registration site
        _native = load_dagcbor_ext()
    except Exception:  # fail-soft: native codec unavailable → pure-Python encoder/decoder, bit-identical by contract
        _native = None
    return _native


def decode(data: bytes) -> Any:
    """Decode one DAG-CBOR item; uses the C extension when available
    (bulk witness/receipt decode is the host-side hot loop)."""
    native = _native if _native is not False else _resolve_native()
    if native is not None:
        return native.decode(bytes(data))
    return decode_py(data)


@_contextmanager
def force_python_decoder():
    """Context manager routing :func:`decode` through the pure-Python
    decoder for its duration. Benchmarks measuring the scalar reference
    architecture use this so "per-event Python decode" means what it says —
    otherwise the C extension silently accelerates the baseline and the
    reported speedup tracks the extension's build flags, not the design."""
    global _native
    saved = _native
    _native = None
    try:
        yield
    finally:
        _native = saved


def decode_prefix(data: bytes) -> tuple[Any, int]:
    """Decode one item, returning ``(value, bytes_consumed)`` (no trailing check)."""
    return _decode_item(bytes(data), 0)
