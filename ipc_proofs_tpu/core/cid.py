"""CIDv1 with dag-cbor/raw codecs and blake2b-256/sha2-256 multihashes.

Replaces the reference's ``cid`` + ``multihash-codetable`` crates. Filecoin
chain CIDs are CIDv1 / dag-cbor / blake2b-256; strings are multibase
base32-lower ("b" prefix), e.g. ``bafy2bza...``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import total_ordering

from ipc_proofs_tpu.core.hashes import blake2b_256
from ipc_proofs_tpu.core.varint import decode_uvarint_min, encode_uvarint

# codecs
DAG_CBOR = 0x71
RAW = 0x55

# multihash codes
BLAKE2B_256 = 0xB220
SHA2_256 = 0x12
KECCAK_256 = 0x1B
IDENTITY = 0x00

__all__ = [
    "CID",
    "DAG_CBOR",
    "RAW",
    "BLAKE2B_256",
    "SHA2_256",
    "KECCAK_256",
    "IDENTITY",
    "cids_from_strings",
    "cid_strings",
]

# RFC 4648 base32 via Python's C-level big-int parser/formatter: ~5x faster
# than base64.b32encode/b32decode, which matters because the verifier parses
# two CID strings per proof group and the generator renders one per claim.
_B32_ALPHABET = "abcdefghijklmnopqrstuvwxyz234567"
# int(x, 32) uses digits 0-9a-v; translate the (lowercase-only — multibase
# 'b' is base32-lower) RFC4648 alphabet onto them
_B32_TO_INT32 = str.maketrans(_B32_ALPHABET, "0123456789abcdefghijklmnopqrstuv")


# 10-bit → 2-char lookup halves the per-call loop length vs per-char
_B32_PAIRS = [a + b for a in _B32_ALPHABET for b in _B32_ALPHABET]


def _b32_encode_lower(data: bytes) -> str:
    nbits = len(data) * 8
    n_chars = (nbits + 4) // 5
    n_pairs = (n_chars + 1) // 2
    value = int.from_bytes(data, "big") << (n_pairs * 10 - nbits)
    pairs = _B32_PAIRS
    out = "".join(
        [pairs[(value >> s) & 1023] for s in range((n_pairs - 1) * 10, -1, -10)]
    )
    return out[:n_chars]


_B32_CHARSET = frozenset(_B32_ALPHABET)


def _b32_decode_lower(text: str) -> bytes:
    """STRICT base32-lower decode: every accepted string is the unique
    canonical encoding of its bytes. Multibase prefix 'b' means
    base32-LOWER, and the reference stack (Rust multibase/data-encoding)
    rejects mixed case, non-canonical lengths, and non-zero trailing bits
    — each a way for distinct strings to decode to one CID (string→CID
    malleability). The C batch parser enforces the same three rules."""
    if not text:
        return b""
    # RFC 4648 unpadded lengths are ≡ {0,2,4,5,7} (mod 8); the others cannot
    # arise from encoding
    if len(text) % 8 in (1, 3, 6):
        raise ValueError(f"invalid base32 length {len(text)}")
    # membership check BEFORE the int parse: characters outside the
    # lowercase RFC alphabet that happen to be base-32 int digits
    # ('0','1','8','9', uppercase) pass through translate untouched and
    # int() accepts them — '0' aliasing 'a', '8' aliasing 'i', etc.
    # (found by tests/test_codec_exec_fuzz.py)
    if not _B32_CHARSET.issuperset(text):
        raise ValueError(f"non-base32 character in {text!r}")
    value = int(text.translate(_B32_TO_INT32), 32)
    nbits = len(text) * 5
    nbytes = nbits // 8
    if value & ((1 << (nbits - nbytes * 8)) - 1):
        raise ValueError(f"non-zero trailing bits in base32 {text!r}")
    return (value >> (nbits - nbytes * 8)).to_bytes(nbytes, "big")


def cids_from_strings(texts) -> "list[CID]":
    """Parse many CID strings in one batched C call when the extension is
    available (`CID.from_string` semantics, including every rejection);
    scalar fallback otherwise. The verifier parses 2-3 strings per proof
    group — batching them is ~30× cheaper than the int-codec loop."""
    from ipc_proofs_tpu.backend.native import load_dagcbor_ext

    ext = load_dagcbor_ext()
    if ext is not None and hasattr(ext, "cids_from_strs"):
        return ext.cids_from_strs(list(texts))
    return [CID.from_string(t) for t in texts]


def cid_strings(cids) -> "list[str]":
    """Render many CIDs as multibase strings in one batched C call when
    available (`CID.__str__` semantics); scalar fallback otherwise."""
    from ipc_proofs_tpu.backend.native import load_dagcbor_ext

    ext = load_dagcbor_ext()
    if ext is not None and hasattr(ext, "cid_strs"):
        return ext.cid_strs([c.to_bytes() for c in cids])
    return [str(c) for c in cids]


@total_ordering
@dataclass(frozen=True)
class CID:
    """An immutable CIDv1 (version, codec, multihash code, digest)."""

    version: int
    codec: int
    mh_code: int
    digest: bytes

    # --- constructors ------------------------------------------------------

    @classmethod
    def _make(cls, version: int, codec: int, mh_code: int, digest: bytes) -> "CID":
        """Internal fast constructor: a frozen dataclass pays four
        ``object.__setattr__`` calls per init, which dominates bulk decode
        paths creating tens of thousands of CIDs per range."""
        out = object.__new__(cls)
        out.__dict__.update(
            version=version, codec=codec, mh_code=mh_code, digest=digest
        )
        return out

    @classmethod
    def hash_of(cls, data: bytes, codec: int = DAG_CBOR, mh_code: int = BLAKE2B_256) -> "CID":
        """CID of raw block bytes (the Filecoin chain default: blake2b-256)."""
        if mh_code == BLAKE2B_256:
            digest = blake2b_256(data)
        elif mh_code == SHA2_256:
            import hashlib

            digest = hashlib.sha256(data).digest()
        elif mh_code == KECCAK_256:
            from ipc_proofs_tpu.core.hashes import keccak256

            digest = keccak256(data)
        elif mh_code == IDENTITY:
            digest = data
        else:
            raise ValueError(f"unsupported multihash code {mh_code:#x}")
        return cls(1, codec, mh_code, digest)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "CID":
        # fast paths: the two canonical chain forms — CIDv1 dag-cbor
        # blake2b-256 (every Filecoin chain block) and CIDv1 raw sha2-256.
        # Decode paths parse these tens of thousands of times per range.
        # On the fast paths ``raw`` is the canonical encoding by
        # construction (fixed minimal-varint prefixes), so it is stashed as
        # the to_bytes memo — witness loading and claim construction
        # re-encode every CID they touch.
        if len(raw) == 38 and raw[1] == 0x71 and raw[:6] == b"\x01\x71\xa0\xe4\x02\x20":
            out = cls._make(1, DAG_CBOR, BLAKE2B_256, raw[6:])
        elif len(raw) == 38 and raw[:6] == b"\x01\x55\xa0\xe4\x02\x20":
            out = cls._make(1, RAW, BLAKE2B_256, raw[6:])
        elif len(raw) == 36 and raw[:4] == b"\x01\x55\x12\x20":
            out = cls._make(1, RAW, SHA2_256, raw[4:])
        else:
            version, off, minimal = decode_uvarint_min(raw)
            if version != 1:
                raise ValueError(f"unsupported CID version {version}")
            codec, off, m = decode_uvarint_min(raw, off)
            minimal &= m
            mh_code, off, m = decode_uvarint_min(raw, off)
            minimal &= m
            mh_len, off, m = decode_uvarint_min(raw, off)
            minimal &= m
            digest = raw[off : off + mh_len]
            if len(digest) != mh_len:
                raise ValueError("truncated CID multihash digest")
            if off + mh_len != len(raw):
                raise ValueError("trailing bytes after CID")
            # strict minimal varints: go-varint and rust unsigned-varint
            # (the reference's CID stack) both reject non-minimal
            # encodings, and tolerating them gives one logical CID two
            # byte forms — the batch/scalar paths then disagree on raw
            # spans vs re-encodes (found by the round-5 exec-order fuzz).
            if not minimal:
                raise ValueError("non-canonical CID byte encoding")
            out = cls._make(version, codec, mh_code, digest)
        # accepted ⇒ canonical encoding (minimal varints enforced above),
        # so raw is always safe to memoize as the to_bytes value
        out.__dict__["_bytes"] = bytes(raw)
        return out

    @classmethod
    def from_string(cls, text: str) -> "CID":
        if not text:
            raise ValueError("empty CID string")
        if text[0] != "b":
            raise ValueError(f"unsupported multibase prefix {text[0]!r} (base32 only)")
        raw = _b32_decode_lower(text[1:])
        out = cls.from_bytes(raw)
        # belt-and-braces canonical check: from_bytes itself rejects
        # non-minimal varints, so any accepted decode re-encodes to `raw`;
        # the compare is kept as defense in depth at the STRING boundary
        # where claims live (memoized on the fast paths, so it is cheap).
        if out.to_bytes() != raw:
            raise ValueError(f"non-canonical CID byte encoding in {text!r}")
        return out

    @classmethod
    def parse(cls, value: "CID | str | bytes") -> "CID":
        # CID_TYPES (module-bottom) covers BOTH implementations, so a
        # native CID handed to PurePythonCID.parse passes through unchanged
        # just like the native parse accepts a pure instance
        if isinstance(value, CID_TYPES):
            return value
        if isinstance(value, bytes):
            return cls.from_bytes(value)
        return cls.from_string(value)

    # --- serialization -----------------------------------------------------

    # precomputed varint prefixes for the canonical 32-byte-digest forms
    _PREFIXES = {
        (1, DAG_CBOR, BLAKE2B_256): b"\x01\x71\xa0\xe4\x02\x20",
        (1, RAW, BLAKE2B_256): b"\x01\x55\xa0\xe4\x02\x20",
        (1, RAW, SHA2_256): b"\x01\x55\x12\x20",
        (1, DAG_CBOR, SHA2_256): b"\x01\x71\x12\x20",
    }

    def to_bytes(self) -> bytes:
        cached = self.__dict__.get("_bytes")
        if cached is None:
            prefix = (
                self._PREFIXES.get((self.version, self.codec, self.mh_code))
                if len(self.digest) == 32
                else None
            )
            if prefix is not None:
                cached = prefix + self.digest
            else:
                cached = (
                    encode_uvarint(self.version)
                    + encode_uvarint(self.codec)
                    + encode_uvarint(self.mh_code)
                    + encode_uvarint(len(self.digest))
                    + self.digest
                )
            object.__setattr__(self, "_bytes", cached)  # frozen-safe memo
        return cached

    def __str__(self) -> str:
        cached = self.__dict__.get("_str")
        if cached is None:
            cached = "b" + _b32_encode_lower(self.to_bytes())
            object.__setattr__(self, "_str", cached)  # frozen-safe memo
        return cached

    def __repr__(self) -> str:
        return f"CID({str(self)})"

    def __lt__(self, other: "CID") -> bool:
        return self.to_bytes() < other.to_bytes()

    def __hash__(self) -> int:  # dataclass frozen gives eq; keep hash cheap
        cached = self.__dict__.get("_hash")
        if cached is None:
            cached = hash(self.digest)
            object.__setattr__(self, "_hash", cached)  # frozen-safe memo
        return cached


# --- native CID binding ----------------------------------------------------
# The C extension ships a C-slot CID type (ipc_dagcbor_ext.CID) with this
# exact interface: same constructor signature, classmethods, comparison /
# hash semantics, and the same strict-canonical acceptance at the bytes and
# string boundaries. The dataclass above stays the correctness reference
# (exported as PurePythonCID; the full suite runs against it under
# IPC_PROOFS_NO_NATIVE) — but per-instance it pays a __dict__ plus a dict
# insert per field and per memo, which dominated bulk decode paths at
# ~2.9 µs/header (NOTES_r04 "verify_replay stage floor"). When the
# extension is importable, CID *is* the native type, so every constructor
# in the tree (header links, witness materialization, claim parsing) gets
# C-slot construction without call sites changing.

PurePythonCID = CID

__all__.append("PurePythonCID")


def _bind_native_cid():
    # via core._cid_native (stdlib-only), NOT the backend package: importing
    # backend here would transitively import modules that capture the
    # pure-Python CID before the rebind below lands
    try:
        import ipc_proofs_tpu.core._cid_native as _cid_native

        ext = _cid_native.load()  # honors IPC_PROOFS_NO_NATIVE itself
    except Exception:  # fail-soft: import/build failure keeps the pure-Python CID class, bit-identical by contract
        return None
    return getattr(ext, "CID", None) if ext is not None else None


_native_cid = _bind_native_cid()
if _native_cid is not None:
    CID = _native_cid  # type: ignore[misc]

# Every type that IS a CID in this process — both implementations coexist
# in differential tests and fixture builders, and boundaries that accept
# user-held CIDs (dagcbor.encode, parse) must recognize either.
CID_TYPES: "tuple[type, ...]" = (
    (CID, PurePythonCID) if CID is not PurePythonCID else (CID,)
)
__all__.append("CID_TYPES")
