"""Hash primitives: blake2b-256 (Filecoin CIDs) and keccak256 (EVM).

Replaces the reference's ``sha3``/``tiny-keccak`` (reference
``src/proofs/common/evm.rs:81-88``) and the Blake2b-256 multihash used for
every Filecoin chain CID (``src/proofs/events/utils.rs:65``).

The scalar paths here are the *reference implementations*; the batch paths
live behind :mod:`ipc_proofs_tpu.backend` (C++ on CPU, Pallas/JAX on TPU) and
are tested for equality against these.
"""

from __future__ import annotations

import hashlib

__all__ = ["blake2b_256", "keccak256", "keccak_f1600"]


def blake2b_256(data: bytes) -> bytes:
    """Blake2b with a 32-byte digest — Filecoin's chain CID hash function."""
    return hashlib.blake2b(data, digest_size=32).digest()


# --- Keccak-256 (the pre-NIST sha3 variant used by Ethereum/EVM) -----------

_MASK = (1 << 64) - 1

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] for lane A[x, y] (state index x + 5*y).
_ROTATION = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)


def _rotl64(value: int, shift: int) -> int:
    if shift == 0:
        return value
    return ((value << shift) | (value >> (64 - shift))) & _MASK


def keccak_f1600(state: list[int]) -> list[int]:
    """Apply the 24-round keccak-f[1600] permutation to 25 u64 lanes.

    Lane layout: ``state[x + 5 * y]``. This scalar version is the golden
    model for the JAX/Pallas u32-pair kernels in
    :mod:`ipc_proofs_tpu.ops.keccak_jax`.
    """
    a = state
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl64(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[i] ^ d[i % 5] for i in range(25)]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl64(a[x + 5 * y], _ROTATION[x][y])
        # chi
        a = [
            b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y] & _MASK) & b[(x + 2) % 5 + 5 * y])
            for y in range(5)
            for x in range(5)
        ]
        # iota
        a[0] ^= rc
    return a


_RATE = 136  # bytes; 1088-bit rate for 256-bit output


def keccak256(data: bytes) -> bytes:
    """Keccak-256 of ``data`` (EVM event-signature / storage-slot hashing)."""
    # multi-rate padding 0x01 .. 0x80 (keccak, NOT the 0x06 sha3 variant)
    padded = bytearray(data)
    pad_len = _RATE - (len(data) % _RATE)
    padded += b"\x00" * pad_len
    padded[len(data)] |= 0x01
    padded[-1] |= 0x80

    state = [0] * 25
    for block_start in range(0, len(padded), _RATE):
        block = padded[block_start : block_start + _RATE]
        for i in range(_RATE // 8):
            state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        state = keccak_f1600(state)

    out = bytearray()
    for i in range(4):  # 32 bytes = 4 lanes
        out += state[i].to_bytes(8, "little")
    return bytes(out)
