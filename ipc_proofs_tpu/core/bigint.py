"""Filecoin BigInt (TokenAmount) byte serialization.

Matches ``fvm_shared::bigint`` CBOR form: a byte string that is empty for
zero, else a sign byte (0x00 positive / 0x01 negative) followed by the
big-endian magnitude (no leading zero bytes).
"""

from __future__ import annotations

__all__ = ["bigint_to_bytes", "bigint_from_bytes"]


def bigint_to_bytes(value: int) -> bytes:
    if value == 0:
        return b""
    sign = b"\x00" if value > 0 else b"\x01"
    magnitude = abs(value)
    return sign + magnitude.to_bytes((magnitude.bit_length() + 7) // 8, "big")


def bigint_from_bytes(data: bytes) -> int:
    if not data:
        return 0
    sign = data[0]
    magnitude = int.from_bytes(data[1:], "big")
    if sign == 0x00:
        return magnitude
    if sign == 0x01:
        return -magnitude
    raise ValueError(f"invalid BigInt sign byte {sign:#x}")
