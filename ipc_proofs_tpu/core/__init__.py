"""Core IPLD byte layer: varint, CID, canonical DAG-CBOR, hashes.

Replaces the reference's external crates (`cid`, `multihash-codetable`,
`serde_ipld_dagcbor`, `fvm_ipld_encoding`, `sha3` — reference Cargo.toml:10-39)
with a self-contained implementation. Byte-exactness here is load-bearing:
every proof CID above this layer depends on it.
"""

from ipc_proofs_tpu.core.varint import encode_uvarint, decode_uvarint
from ipc_proofs_tpu.core.cid import CID, DAG_CBOR, RAW, BLAKE2B_256, SHA2_256
from ipc_proofs_tpu.core.dagcbor import encode as cbor_encode, decode as cbor_decode
from ipc_proofs_tpu.core.hashes import keccak256, blake2b_256

__all__ = [
    "encode_uvarint",
    "decode_uvarint",
    "CID",
    "DAG_CBOR",
    "RAW",
    "BLAKE2B_256",
    "SHA2_256",
    "cbor_encode",
    "cbor_decode",
    "keccak256",
    "blake2b_256",
]
