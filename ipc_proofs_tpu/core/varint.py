"""Unsigned LEB128 varints as used by multiformats (CID, multihash, addresses)."""

from __future__ import annotations


def encode_uvarint(value: int) -> bytes:
    """Encode a non-negative integer as an unsigned LEB128 varint."""
    if value < 0:
        raise ValueError("uvarint cannot encode negative values")
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def decode_uvarint_min(data: bytes, offset: int = 0) -> tuple[int, int, bool]:
    """``decode_uvarint`` plus a minimality flag: ``(value, new_offset,
    minimal)``. A multi-byte varint whose final (most-significant) byte is
    zero is a second encoding of the same value; go-varint and rust
    unsigned-varint both reject it, and so do this package's CID decoders
    (mirrors the C extensions' ``cid_uvarint_min``)."""
    value, pos = decode_uvarint(data, offset)
    return value, pos, pos - offset == 1 or data[pos - 1] != 0


def decode_uvarint(data: bytes, offset: int = 0) -> tuple[int, int]:
    """Decode an unsigned LEB128 varint from ``data`` at ``offset``.

    Returns ``(value, new_offset)``.
    """
    value = 0
    shift = 0
    pos = offset
    while True:
        if pos >= len(data):
            raise ValueError("truncated uvarint")
        byte = data[pos]
        pos += 1
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint too long")
