"""Bulk backfill: prove deep history as a durable, streaming batch job.

ROADMAP item 4. Interactive serving and standing-query pushes answer
"prove THIS tipset"; backfill answers "prove every matching event over
the last 100k epochs" — the one workload big enough to saturate a
device mesh. The design follows the parallel-EVM-with-async-storage
blueprint (PAPERS.md, arxiv 2503.04595): epoch-partitioned execution
fed by a work-ahead storage scheduler instead of one demand-driven
chunk spine, streaming verified chunks to clients as they land
(stateless-client line, arxiv 2504.14069) rather than holding results
until job completion.

- `scheduler.py` — epoch windows on ring arcs (`cluster/hashring.py`
  placement) + the `WorkAheadFeeder` that primes the fetch plane's
  speculative lanes from the schedule across window boundaries;
- `engine.py`   — `BackfillEngine`/`BackfillJob`: IPJ1 journal
  durability per job (SIGKILL-resumable, byte-identical by
  construction), incremental `BundleFold` merge, cursor-protocol chunk
  streaming, standing-query catch-up landing, and a pluggable
  ``run_window`` so execution rides the serve plane's low-priority
  micro-batcher lane or the cluster router's steal-aware dispatch.

HTTP surface (`serve/httpd.py`, mirrored by the cluster router):
``POST /v1/backfill`` submits, ``GET /v1/backfill/<id>`` reports
status, ``GET /v1/backfill/<id>/chunks?cursor=N&wait_s=S`` long-polls
chunks with ack-through-cursor semantics. See README "Bulk backfill".
"""

from ipc_proofs_tpu.backfill.engine import (
    BackfillChunk,
    BackfillEngine,
    BackfillError,
    BackfillJob,
    local_window_runner,
)
from ipc_proofs_tpu.backfill.scheduler import (
    EpochWindow,
    WorkAheadFeeder,
    plan_windows,
    window_ring_key,
)

__all__ = [
    "BackfillChunk",
    "BackfillEngine",
    "BackfillError",
    "BackfillJob",
    "EpochWindow",
    "WorkAheadFeeder",
    "local_window_runner",
    "plan_windows",
    "window_ring_key",
]
