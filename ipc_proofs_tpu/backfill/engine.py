"""Durable backfill jobs: prove deep history as a resumable batch job.

`BackfillEngine` answers "prove every matching event for this service's
filter over epochs [start, end)" as a first-class job rather than one
giant interactive request:

- **planning** — the range splits into epoch windows on ring arcs
  (`backfill/scheduler.py`); a `WorkAheadFeeder` primes the fetch
  plane's speculative lanes from the schedule so device-side batches
  never drain at window boundaries.
- **durability** — each job owns one IPJ1 write-ahead journal
  (`ipc_proofs_tpu.jobs`): the manifest binds the directory to the
  exact request (spec + pair range + window size, the same
  ``_request_spec_repr`` discipline the chunked driver uses), and every
  completed window commits one fsync'd chunk record under its window
  index. A SIGKILL at any instant loses at most the in-flight windows;
  re-submitting the same range resumes from the journal and produces
  the same final bytes — window bundles are pure functions of their
  pairs, so replayed and regenerated windows are interchangeable.
- **streaming** — window bundles fold through
  `cluster/gather.py::BundleFold` (one CID map, one sort at seal) AND
  stream to the caller as verified chunks under monotonic cursors, the
  `subs/delivery.py` long-poll contract: polling from cursor N acks
  everything ≤ N (payloads dropped from memory; the journal keeps the
  bytes) and returns what's above it. The first chunk is available as
  soon as the first window commits — long before job completion.
- **priority** — the engine never executes proofs itself; it calls a
  ``run_window`` callable. The serve wiring passes the micro-batcher's
  LOW-priority lane (`ProofService.submit_range_window`), the cluster
  wiring the router's steal-aware dispatch, so a 100k-epoch job shares
  devices with interactive traffic instead of starving it.

Byte identity: the sealed result equals
`generate_event_proofs_for_range_chunked` over the same pairs for ANY
window size, shard count, or completion order — the gather merge law
(pair-ordered proof buckets + one sorted witness-CID union) is
partition-independent, which the differential grid in
``tests/test_backfill.py`` pins.
"""

from __future__ import annotations

import hashlib
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable, Optional, Sequence

from ipc_proofs_tpu.backfill.scheduler import (
    EpochWindow,
    WorkAheadFeeder,
    plan_windows,
)
from ipc_proofs_tpu.cluster.gather import BundleFold
from ipc_proofs_tpu.proofs.bundle import UnifiedProofBundle
from ipc_proofs_tpu.proofs.range import (
    _chunk_checkpoint_digest,
    _request_spec_repr,
    generate_event_proofs_for_range_chunked,
)
from ipc_proofs_tpu.utils.lockdep import named_condition, named_lock
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.threads import locked
from ipc_proofs_tpu.utils.metrics import Metrics, get_metrics

log = get_logger(__name__)

__all__ = [
    "BackfillChunk",
    "BackfillEngine",
    "BackfillError",
    "BackfillJob",
    "local_window_runner",
]


class BackfillError(RuntimeError):
    """The job failed (a window runner raised) or was cancelled by
    shutdown; committed windows stay journalled for resume."""


class BackfillChunk:
    """One streamed result chunk: a window's bundle under its cursor.

    ``bundle_obj`` (the canonical JSON object) is dropped when the
    cursor is acked — the journal keeps the bytes; the in-memory entry
    keeps only the digest and window metadata for status/history.
    """

    __slots__ = ("cursor", "window", "digest", "n_event_proofs", "bundle_obj")

    def __init__(
        self,
        cursor: int,
        window: EpochWindow,
        digest: str,
        n_event_proofs: int,
        bundle_obj: Optional[dict],
    ):
        self.cursor = cursor
        self.window = window
        self.digest = digest
        self.n_event_proofs = n_event_proofs
        self.bundle_obj = bundle_obj

    def to_json_obj(self, with_bundle: bool = True) -> dict:
        obj = {
            "cursor": self.cursor,
            "window": self.window.to_json_obj(),
            "digest": self.digest,
            "n_event_proofs": self.n_event_proofs,
        }
        if with_bundle and self.bundle_obj is not None:
            obj["bundle"] = self.bundle_obj
        return obj


class BackfillJob:
    """One submitted backfill: windows, cursor log, final sealed bundle.

    State machine: ``running`` → ``complete`` | ``failed``. A failed or
    shutdown-interrupted job is resumable — re-submitting the identical
    range lands on the same journal directory and replays committed
    windows instead of regenerating them.
    """

    def __init__(
        self,
        job_id: str,
        start: int,
        end: int,
        window_size: int,
        windows: Sequence[EpochWindow],
        sub_id: Optional[str] = None,
    ):
        self.job_id = job_id
        self.start = start
        self.end = end
        self.window_size = window_size
        self.windows = list(windows)
        self.sub_id = sub_id
        self.submitted_at = time.monotonic()
        # lock-order: BackfillJob._cond is leaf — nothing else is
        # acquired while it is held (journal/fold/runner calls all
        # happen outside it)
        self._cond = named_condition("BackfillJob._cond")
        self.state = "running"  # guarded-by: _cond
        self.error: Optional[str] = None  # guarded-by: _cond
        self._chunks: "list[BackfillChunk]" = []  # guarded-by: _cond
        self._acked = 0  # highest acked cursor; guarded-by: _cond
        self._replayed = 0  # windows satisfied from the journal; guarded-by: _cond
        self._first_chunk_s: Optional[float] = None  # guarded-by: _cond
        self._result: Optional[UnifiedProofBundle] = None  # guarded-by: _cond
        # proving seconds summed across runner threads (replayed windows
        # add none) — busy_s / (lanes × wall_s) is lane occupancy
        self._busy_s = 0.0  # guarded-by: _cond
        self._wall_s: Optional[float] = None  # guarded-by: _cond

    # --- mutation (engine runner thread only) ------------------------------

    def _emit(self, chunk: BackfillChunk, replayed: bool) -> None:
        with self._cond:
            chunk.cursor = len(self._chunks) + 1
            self._chunks.append(chunk)
            if replayed:
                self._replayed += 1
            if self._first_chunk_s is None:
                self._first_chunk_s = time.monotonic() - self.submitted_at
            self._cond.notify_all()

    def _finish(self, result: UnifiedProofBundle) -> None:
        with self._cond:
            self._result = result
            self.state = "complete"
            self._wall_s = time.monotonic() - self.submitted_at
            self._cond.notify_all()

    def _fail(self, error: str) -> None:
        with self._cond:
            self.error = error
            self.state = "failed"
            self._wall_s = time.monotonic() - self.submitted_at
            self._cond.notify_all()

    def _add_busy(self, seconds: float) -> None:
        with self._cond:
            self._busy_s += seconds

    # --- cursor protocol ----------------------------------------------------

    def chunks_after(
        self, cursor: int, wait_s: float = 0.0, limit: int = 64
    ) -> dict:
        """Long-poll chunk fetch, the `subs/delivery.py` contract: a
        client asking from cursor N owns everything ≤ N (those chunk
        payloads are dropped from memory — the journal keeps the bytes)
        and receives up to ``limit`` chunks above it, blocking up to
        ``wait_s`` for the first one. Returns immediately once the job
        left ``running`` — a finished job has nothing more to wait for.
        """
        deadline = time.monotonic() + max(0.0, wait_s)
        with self._cond:
            self._ack_locked(cursor)
            while True:
                fresh = [c for c in self._chunks if c.cursor > cursor][:limit]
                if fresh or self.state != "running":
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            return {
                "job_id": self.job_id,
                "state": self.state,
                "cursor": len(self._chunks),
                "acked": self._acked,
                "chunks": [c.to_json_obj() for c in fresh],
            }

    def ack_through(self, cursor: int) -> int:
        """Drop streamed payloads with cursor ≤ ``cursor``; returns how
        many were dropped (idempotent — already-acked cursors skip)."""
        with self._cond:
            return self._ack_locked(cursor)

    @locked  # every caller holds self._cond
    def _ack_locked(self, cursor: int) -> int:
        dropped = 0
        for c in self._chunks:
            if c.cursor > cursor:
                break
            if c.bundle_obj is not None:
                c.bundle_obj = None
                dropped += 1
        if cursor > self._acked:
            self._acked = min(cursor, len(self._chunks))
        return dropped

    # --- status / result ----------------------------------------------------

    def status(self) -> dict:
        with self._cond:
            done = len(self._chunks)
            return {
                "job_id": self.job_id,
                "state": self.state,
                "error": self.error,
                "pair_start": self.start,
                "pair_end": self.end,
                "n_pairs": self.end - self.start,
                "window_size": self.window_size,
                "windows_total": len(self.windows),
                "windows_done": done,
                "windows_replayed": self._replayed,
                "epochs_done": sum(
                    c.window.n_epochs for c in self._chunks
                ),
                "cursor": done,
                "acked": self._acked,
                "first_chunk_s": self._first_chunk_s,
                "busy_s": self._busy_s,
                "wall_s": (
                    self._wall_s
                    if self._wall_s is not None
                    else time.monotonic() - self.submitted_at
                ),
                "sub_id": self.sub_id,
                "nodes": sorted({w.node for w in self.windows}),
            }

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the job leaves ``running``; True when it did."""
        deadline = (
            (time.monotonic() + timeout) if timeout is not None else None
        )
        with self._cond:
            while self.state == "running":
                remaining = (
                    (deadline - time.monotonic()) if deadline is not None else None
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    def result(self, timeout: Optional[float] = None) -> UnifiedProofBundle:
        """The sealed final bundle — byte-identical to the chunked range
        driver over the same pairs. Raises `BackfillError` on failure or
        `TimeoutError` if the job is still running after ``timeout``."""
        if not self.wait(timeout):
            raise TimeoutError(
                f"backfill job {self.job_id} still running after wait"
            )
        with self._cond:
            if self.state != "complete":
                raise BackfillError(
                    f"backfill job {self.job_id} {self.state}: {self.error}"
                )
            return self._result


def local_window_runner(
    store,
    spec,
    chunk_size: Optional[int] = None,
    match_backend=None,
    metrics: Optional[Metrics] = None,
) -> "Callable[[EpochWindow, list], UnifiedProofBundle]":
    """Window runner for a standalone engine (CLI, tests): each window
    runs the canonical chunked driver directly. ``chunk_size`` defaults
    to the whole window (one chunk per window)."""

    def run(window: EpochWindow, pairs: list) -> UnifiedProofBundle:
        return generate_event_proofs_for_range_chunked(
            store,
            pairs,
            spec,
            chunk_size=chunk_size or len(pairs),
            metrics=metrics,
            match_backend=match_backend,
        )

    return run


class BackfillEngine:
    """Plan, journal, execute, and stream backfill jobs.

    ``run_window(window, pairs) -> UnifiedProofBundle`` is the only
    execution dependency — the engine itself never touches a device,
    which is what lets the same core drive the CLI (direct driver), the
    serve daemon (low-priority micro-batcher lane) and the cluster
    router (steal-aware shard dispatch).
    """

    def __init__(
        self,
        pairs: Sequence,
        spec,
        run_window: "Callable[[EpochWindow, list], UnifiedProofBundle]",
        jobs_dir: Optional[str] = None,
        window_size: int = 8,
        work_ahead: int = 2,
        window_parallelism: int = 1,
        nodes: Sequence[str] = ("local",),
        plane=None,
        metrics: Optional[Metrics] = None,
        delivery=None,
        fsync: bool = True,
    ):
        if window_size < 1:
            raise ValueError("window_size must be >= 1")
        self.pairs = list(pairs)
        self.spec = spec
        self.run_window = run_window
        self.jobs_dir = jobs_dir
        self.window_size = int(window_size)
        self.work_ahead = max(0, int(work_ahead))
        self.window_parallelism = max(1, int(window_parallelism))
        self.nodes = list(nodes)
        self.plane = plane
        self.metrics = metrics if metrics is not None else get_metrics()
        self.delivery = delivery  # subs.DeliveryLog for catch-up landing
        self.fsync = fsync
        self._lock = named_lock("BackfillEngine._lock")
        self._jobs: "dict[str, BackfillJob]" = {}  # guarded-by: _lock
        self._threads: "dict[str, threading.Thread]" = {}  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock

    # --- submission ---------------------------------------------------------

    def _job_id(self, manifest: dict) -> str:
        ident = manifest["params_digest"] + manifest["range_digest"]
        return "bf-" + hashlib.sha256(ident.encode()).hexdigest()[:12]

    def submit(
        self,
        start: int,
        end: int,
        window_size: Optional[int] = None,
        sub_id: Optional[str] = None,
    ) -> BackfillJob:
        """Plan and launch one job over global pairs ``[start, end)``.

        Idempotent: the job id derives from the journal manifest (spec +
        pair range + window size), so re-submitting an identical range
        returns the live job if one is running, or resumes the journal
        of a finished/crashed one.
        """
        wsize = int(window_size or self.window_size)
        windows = plan_windows(self.pairs, start, end, wsize, self.nodes)
        job_pairs = self.pairs[start:end]
        from ipc_proofs_tpu.jobs import job_manifest

        # a spec-less engine (the cluster router: one deployment serves
        # one spec, fixed on the shards) still binds the manifest to the
        # window size; pair identity rides the manifest's range_digest
        spec_repr = (
            _request_spec_repr(self.spec, wsize, None)
            if self.spec is not None
            else repr(("backfill-opaque-spec", wsize)).encode()
        )
        manifest = job_manifest(spec_repr, job_pairs, wsize)
        job_id = self._job_id(manifest)
        with self._lock:
            if self._closed:
                raise BackfillError("backfill engine is closed")
            existing = self._jobs.get(job_id)
            if existing is not None and existing.state == "running":
                return existing
            job = BackfillJob(job_id, start, end, wsize, windows, sub_id=sub_id)
            self._jobs[job_id] = job
            thread = threading.Thread(
                target=self._run_job,
                args=(job, manifest, spec_repr),
                name=f"backfill-{job_id}",
                daemon=True,
            )
            self._threads[job_id] = thread
            n_active = sum(
                1 for j in self._jobs.values() if j.state == "running"
            )
        self.metrics.count("backfill.jobs")
        self.metrics.set_gauge("backfill.active_jobs", n_active)
        thread.start()
        return job

    def job(self, job_id: str) -> Optional[BackfillJob]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> "list[dict]":
        with self._lock:
            jobs = list(self._jobs.values())
        return [j.status() for j in jobs]

    # --- execution ----------------------------------------------------------

    def _open_journal(self, job: BackfillJob, manifest: dict):
        if self.jobs_dir is None:
            return None
        import os

        from ipc_proofs_tpu.jobs import resume_or_create

        job_dir = os.path.join(self.jobs_dir, job.job_id)
        os.makedirs(job_dir, exist_ok=True)
        return resume_or_create(
            job_dir, manifest, metrics=self.metrics, fsync=self.fsync
        )

    def _run_job(self, job: BackfillJob, manifest: dict, spec_repr: bytes) -> None:
        journal = None
        try:
            journal = self._open_journal(job, manifest)
            fold = BundleFold(
                self.pairs, list(range(job.start, job.end)), metrics=self.metrics
            )
            digests = {
                w.index: _chunk_checkpoint_digest(
                    spec_repr, self.pairs[w.lo : w.hi]
                )
                for w in job.windows
            }
            done: "set[int]" = set()
            # resume: replay committed windows straight into the fold and
            # the cursor log — a reconnecting client streams them from
            # cursor 0 exactly like fresh ones
            if journal is not None:
                resumed = False
                for w in job.windows:
                    if not journal.has_chunk(w.index):
                        continue
                    obj = journal.bundle_obj(w.index, digests[w.index])
                    bundle = UnifiedProofBundle.from_json_obj(obj)
                    fold.fold(bundle)
                    done.add(w.index)
                    self._emit_chunk(job, w, digests[w.index], bundle, obj, True)
                    resumed = True
                if resumed:
                    self.metrics.count("backfill.jobs_resumed")
            feeder = WorkAheadFeeder(
                self.plane, self.pairs, job.windows, work_ahead=self.work_ahead
            )
            pending = [w for w in job.windows if w.index not in done]
            self._run_windows(job, journal, fold, digests, done, feeder, pending)
            job._finish(fold.seal())
        except BaseException as exc:  # fail-soft: the job records its failure; committed windows stay journalled for resume
            self.metrics.count("backfill.window_failures")
            log.warning("backfill job %s failed: %s", job.job_id, exc)
            job._fail(f"{type(exc).__name__}: {exc}")
        finally:
            if journal is not None:
                journal.close()
            with self._lock:
                n_active = sum(
                    1 for j in self._jobs.values() if j.state == "running"
                )
            self.metrics.set_gauge("backfill.active_jobs", n_active)

    def _run_windows(
        self, job, journal, fold, digests, done, feeder, pending
    ) -> None:
        """Execute pending windows at ``window_parallelism``, committing
        and streaming each in COMPLETION order (the fold is
        order-independent; the journal keys records by window index)."""

        def _commit(w: EpochWindow, bundle: UnifiedProofBundle) -> None:
            if journal is not None:
                journal.commit_chunk(w.index, digests[w.index], bundle)
            fold.fold(bundle)
            self._emit_chunk(job, w, digests[w.index], bundle, None, False)

        if self.window_parallelism == 1:
            for w in pending:
                self._check_open(job)
                feeder.on_window_start(w.index, done)
                _commit(w, self._timed_run(job, w))
            return
        executor = ThreadPoolExecutor(
            max_workers=self.window_parallelism,
            thread_name_prefix=f"backfill-{job.job_id}",
        )
        try:
            queue = list(pending)
            futures: dict = {}

            def _launch() -> None:
                if not queue:
                    return
                w = queue.pop(0)
                feeder.on_window_start(w.index, done)
                futures[executor.submit(self._timed_run, job, w)] = w
            for _ in range(self.window_parallelism):
                _launch()
            while futures:
                self._check_open(job)
                finished, _ = wait(futures, return_when=FIRST_COMPLETED)
                for fut in finished:
                    w = futures.pop(fut)
                    _commit(w, fut.result())  # a window error fails the job
                    _launch()
        finally:
            executor.shutdown(wait=True, cancel_futures=True)

    def _timed_run(self, job: BackfillJob, w: EpochWindow) -> UnifiedProofBundle:
        t0 = time.monotonic()
        try:
            return self.run_window(w, self.pairs[w.lo : w.hi])
        finally:
            job._add_busy(time.monotonic() - t0)

    def _check_open(self, job: BackfillJob) -> None:
        with self._lock:
            closed = self._closed
        if closed:
            raise BackfillError(
                f"backfill engine closed with job {job.job_id} in flight "
                "(journalled windows resume on the next submit)"
            )

    def _emit_chunk(
        self, job, window, digest, bundle, bundle_obj, replayed
    ) -> None:
        obj = bundle_obj if bundle_obj is not None else bundle.to_json_obj()
        chunk = BackfillChunk(
            cursor=0,  # assigned by _emit under the job lock
            window=window,
            digest=digest,
            n_event_proofs=len(bundle.event_proofs),
            bundle_obj=obj,
        )
        job._emit(chunk, replayed)
        self.metrics.count(
            "backfill.windows_replayed" if replayed else "backfill.windows"
        )
        self.metrics.count("backfill.epochs", window.n_epochs)
        self.metrics.count("backfill.chunks_streamed")
        if job.sub_id is not None and self.delivery is not None:
            # standing-query catch-up: the window lands as a normal
            # delivery (idempotency dedup absorbs resume replays)
            tipset = int(
                getattr(self.pairs[window.hi - 1].child, "height", 0) or 0
            )
            landed = self.delivery.append(
                job.sub_id,
                tipset,
                digest,
                {
                    "type": "backfill_chunk",
                    "job_id": job.job_id,
                    "cursor": chunk.cursor,
                    "window": window.to_json_obj(),
                    "bundle": obj,
                },
            )
            if landed is not None:
                self.metrics.count("backfill.catchup_deliveries")

    # --- lifecycle ----------------------------------------------------------

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting jobs; running jobs abort at their next window
        boundary (committed windows are already journalled). Idempotent."""
        with self._lock:
            if self._closed:
                threads = []
            else:
                self._closed = True
                threads = list(self._threads.values())
        for t in threads:
            t.join(timeout)

    def __enter__(self) -> "BackfillEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
