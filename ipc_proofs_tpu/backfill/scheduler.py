"""Work-ahead epoch scheduler: windows on ring arcs, lanes kept full.

The backfill engine proves deep history window by window. This module
owns the *shape* of that work:

- `plan_windows` partitions a contiguous pair range into fixed-size
  **epoch windows** and places each window on a ring arc via the same
  `cluster/hashring.py` consistent hashing the serve router uses for
  pair placement. Placement is derived from the window's FIRST pair
  identity (`window_ring_key`), so every process — engine, router,
  offline test — computes the identical window → node map, and a
  cluster backfill lands each window on the shard whose BlockCache is
  already warm for that arc. Like all ring affinity in this repo it is
  a cache hint, never a correctness constraint: the router's
  steal-aware dispatch may override it under imbalance.

- `WorkAheadFeeder` replaces the chunked driver's one-chunk-ahead
  spine offer (`proofs/range.py::_offer_chunk_spine`) with a
  *schedule-driven* feed: when window ``i`` starts executing, the
  headers of the next ``work_ahead`` not-yet-proven windows enter the
  fetch plane through `FetchPlane.prime` — the depth-gate-free lane —
  so the plane's speculative batches stay full ACROSS window
  boundaries even after adaptive backoff has lowered
  ``speculate_depth`` for link-chasing. The feeder never blocks and
  never raises; against a store without a plane it is a no-op.

Windows are the journal's unit of durability (window index == chunk
index in the IPJ1 record stream — see `backfill/engine.py`), so the
planner is deliberately deterministic: same range + window size →
same windows, byte for byte, on every run.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from ipc_proofs_tpu.cluster.hashring import HashRing, pair_ring_key

__all__ = [
    "EpochWindow",
    "WorkAheadFeeder",
    "plan_windows",
    "window_ring_key",
]


@dataclass(frozen=True)
class EpochWindow:
    """One schedulable slice of the backfill range.

    ``lo``/``hi`` are *global* pair-table indexes (half-open), so a
    window names the same epochs on the engine, the router, and every
    shard. ``index`` is the window ordinal within its job — also the
    journal chunk index its bundle commits under. ``node`` is the
    ring-arc owner chosen at planning time.
    """

    index: int
    lo: int
    hi: int
    node: str

    @property
    def n_epochs(self) -> int:
        return self.hi - self.lo

    def to_json_obj(self) -> dict:
        return {
            "index": self.index,
            "lo": self.lo,
            "hi": self.hi,
            "node": self.node,
            "n_epochs": self.n_epochs,
        }


def window_ring_key(pairs: Sequence, lo: int) -> str:
    """Ring key of the window starting at global pair index ``lo``.

    Deliberately THE SAME key interactive traffic for that pair routes
    under (`pair_ring_key`, content-derived): re-submitting the same
    epoch range always lands each window on the same arc, and the
    planned owner is exactly the shard whose BlockCache interactive
    requests for the window's leading pair have already warmed — the
    router's steal-aware dispatch under this key agrees with the plan
    unless imbalance says otherwise.
    """
    return pair_ring_key(pairs[lo])


def plan_windows(
    pairs: Sequence,
    start: int,
    end: int,
    window_size: int,
    nodes: Sequence[str],
    vnodes: int = 64,
) -> "list[EpochWindow]":
    """Partition ``pairs[start:end]`` into windows placed on ring arcs.

    Every caller with the same arguments computes the identical plan
    (sha256 ring points, no process state), which is what lets the
    crash-resume path re-derive window boundaries from the journal
    manifest alone.
    """
    if window_size < 1:
        raise ValueError("window_size must be >= 1")
    if not (0 <= start < end <= len(pairs)):
        raise ValueError(
            f"pair range [{start}, {end}) out of bounds for table of "
            f"{len(pairs)}"
        )
    if not nodes:
        raise ValueError("backfill plan needs at least one node")
    ring = HashRing(nodes, vnodes=vnodes)
    windows: "list[EpochWindow]" = []
    for index, lo in enumerate(range(start, end, window_size)):
        hi = min(lo + window_size, end)
        windows.append(
            EpochWindow(
                index=index,
                lo=lo,
                hi=hi,
                node=ring.node_for(window_ring_key(pairs, lo)),
            )
        )
    return windows


class WorkAheadFeeder:
    """Feed the fetch plane's speculative lanes from the schedule.

    ``plane`` needs a ``prime(cids)`` method (`store.fetchplane
    .FetchPlane`); anything else (including ``None``) disables the
    feeder. ``work_ahead`` is how many future windows' tipset headers
    are primed when a window starts — the plane chases receipt/state
    links from those headers on its own, so this keeps
    ``--speculate-depth`` lanes busy across the boundary where the
    per-chunk spine offer would have gone quiet.
    """

    def __init__(
        self,
        plane,
        pairs: Sequence,
        windows: Sequence[EpochWindow],
        work_ahead: int = 2,
    ):
        self._prime = getattr(plane, "prime", None)
        self._pairs = pairs
        self._windows = list(windows)
        self._work_ahead = max(0, int(work_ahead))
        self._offered: "set[int]" = set()  # window indexes already primed

    def on_window_start(self, index: int, done: Optional[set] = None) -> int:
        """Window ``index`` is about to execute: prime the headers of the
        next ``work_ahead`` windows that are neither done nor already
        primed. Returns the number of windows primed (observability and
        tests; 0 without a plane)."""
        if self._prime is None or self._work_ahead == 0:
            return 0
        primed = 0
        links: list = []
        for w in self._windows[index + 1 :]:
            if primed >= self._work_ahead:
                break
            if w.index in self._offered or (done and w.index in done):
                continue
            for pair in self._pairs[w.lo : w.hi]:
                links.extend(pair.parent.cids)
                links.extend(pair.child.cids)
            self._offered.add(w.index)
            primed += 1
        if links:
            self._prime(links)
        return primed
