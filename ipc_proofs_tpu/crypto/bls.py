"""BLS12-381 aggregate signatures, pure Python.

Fills the reference's open trust boundary: `src/proofs/trust/mod.rs:58,72`
leaves F3 certificate signature verification as TODOs and `src/cert.rs:52-64`
is a placeholder. This module provides the minimum-BLS scheme go-f3 style
certificates need: G1 public keys (48-byte compressed), G2 signatures
(96-byte compressed), same-message aggregation (every signer signs the gpbft
payload), verified with two pairings.

Performance stance: certificate verification runs ONCE per proof bundle, so
this is deliberately straightforward big-int Python (a pairing is ~0.5 s)
rather than a native or vectorized path — the hot loops of this framework
are elsewhere.

Implementation notes / divergences (documented, all testable in-repo):

* Field tower: Fp2 = Fp[u]/(u²+1), Fp6 = Fp2[v]/(v³-ξ) with ξ = u+1,
  Fp12 = Fp6[w]/(w²-v). Optimal-ate Miller loop over |x| (the BLS parameter
  0xd201000000010000) with affine line functions; final exponentiation by
  the INTEGER (p¹²-1)/r. Because the loop omits the negative-x conjugation,
  the computed map is the inverse of the canonical ate pairing — still
  bilinear and non-degenerate, and signature verification only compares
  pairing values, so equality semantics are identical (asserted by the
  bilinearity tests).
* Hash-to-G2 uses RFC 9380 expand_message_xmd(SHA-256) for byte derivation
  but a try-and-increment x-candidate search plus cofactor clearing instead
  of the SSWU/isogeny map. Interoperable-SSWU requires the 3-isogeny
  constant table, which cannot be verified in this zero-egress environment;
  swap `_hash_to_g2_candidate` when vectors are available. The scheme is
  self-consistent and deterministic.
* The G2 cofactor is derived at import from p, r and the G1 cofactor via
  the CM/twist order relations and checked (twist order divisible by r,
  cleared points r-torsion) rather than hard-coded.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

__all__ = [
    "PRIME",
    "CURVE_ORDER",
    "g1_generator",
    "g2_generator",
    "sk_to_pk",
    "sign",
    "verify",
    "aggregate_signatures",
    "aggregate_pubkeys",
    "verify_aggregate_same_message",
    "pop_prove",
    "pop_verify",
    "g1_compress",
    "g1_decompress",
    "g2_compress",
    "g2_decompress",
    "hash_to_g2",
    "pairing",
]

# --- parameters --------------------------------------------------------------

PRIME = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
CURVE_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
_BLS_X = 0xD201000000010000  # |x|; x itself is negative
_H1 = 0x396C8C005555E1568C00AAAB0000AAAB  # G1 cofactor

_P = PRIME
_B = 4  # E: y^2 = x^3 + 4

_G1 = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
_G2 = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# --- Fp ---------------------------------------------------------------------


def _inv(a: int) -> int:
    return pow(a, _P - 2, _P)


# --- Fp2 = Fp[u]/(u^2+1): (c0, c1) ------------------------------------------


def _f2_add(a, b):
    return ((a[0] + b[0]) % _P, (a[1] + b[1]) % _P)


def _f2_sub(a, b):
    return ((a[0] - b[0]) % _P, (a[1] - b[1]) % _P)


def _f2_neg(a):
    return ((-a[0]) % _P, (-a[1]) % _P)


def _f2_mul(a, b):
    a0b0 = a[0] * b[0]
    a1b1 = a[1] * b[1]
    return ((a0b0 - a1b1) % _P, ((a[0] + a[1]) * (b[0] + b[1]) - a0b0 - a1b1) % _P)


def _f2_sqr(a):
    return _f2_mul(a, a)


def _f2_scalar(a, k: int):
    return ((a[0] * k) % _P, (a[1] * k) % _P)


def _f2_inv(a):
    norm = (a[0] * a[0] + a[1] * a[1]) % _P
    ninv = _inv(norm)
    return ((a[0] * ninv) % _P, ((-a[1]) * ninv) % _P)


_F2_ZERO = (0, 0)
_F2_ONE = (1, 0)
_XI = (1, 1)  # u + 1


def _f2_mul_xi(a):
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return ((a[0] - a[1]) % _P, (a[0] + a[1]) % _P)


def _f2_is_larger(y) -> bool:
    """Lexicographic 'larger y' predicate over Fp2 (c1 first, then c0) —
    the single source of the compressed-point sign convention for
    compress, decompress, and hash-to-curve."""
    return y[1] > (_P - 1) // 2 or (y[1] == 0 and y[0] > (_P - 1) // 2)


def _f2_sqrt(a):
    """Square root in Fp2 by the complex method (p ≡ 3 mod 4); None if
    ``a`` is not a square."""
    c0, c1 = a
    if c1 == 0:
        s = pow(c0, (_P + 1) // 4, _P)
        if s * s % _P == c0:
            return (s, 0)
        # c0 is a non-residue: sqrt is purely imaginary, (t u)^2 = -t^2
        t = pow((-c0) % _P, (_P + 1) // 4, _P)
        if (t * t) % _P == (-c0) % _P:
            return (0, t)
        return None
    norm = (c0 * c0 + c1 * c1) % _P
    s = pow(norm, (_P + 1) // 4, _P)
    if (s * s) % _P != norm:
        return None
    inv2 = _inv(2)
    for sign in (s, (-s) % _P):
        re2 = (c0 + sign) * inv2 % _P
        re = pow(re2, (_P + 1) // 4, _P)
        if (re * re) % _P != re2 or re == 0:
            continue
        im = c1 * _inv(2 * re % _P) % _P
        cand = (re, im)
        if _f2_sqr(cand) == (c0 % _P, c1 % _P):
            return cand
    return None


# --- Fp6 = Fp2[v]/(v^3 - xi): (c0, c1, c2) ----------------------------------


def _f6_add(a, b):
    return (_f2_add(a[0], b[0]), _f2_add(a[1], b[1]), _f2_add(a[2], b[2]))


def _f6_sub(a, b):
    return (_f2_sub(a[0], b[0]), _f2_sub(a[1], b[1]), _f2_sub(a[2], b[2]))


def _f6_neg(a):
    return (_f2_neg(a[0]), _f2_neg(a[1]), _f2_neg(a[2]))


def _f6_mul(a, b):
    t0 = _f2_mul(a[0], b[0])
    t1 = _f2_mul(a[1], b[1])
    t2 = _f2_mul(a[2], b[2])
    c0 = _f2_add(t0, _f2_mul_xi(_f2_sub(_f2_mul(_f2_add(a[1], a[2]), _f2_add(b[1], b[2])), _f2_add(t1, t2))))
    c1 = _f2_add(
        _f2_sub(_f2_mul(_f2_add(a[0], a[1]), _f2_add(b[0], b[1])), _f2_add(t0, t1)),
        _f2_mul_xi(t2),
    )
    c2 = _f2_add(_f2_sub(_f2_mul(_f2_add(a[0], a[2]), _f2_add(b[0], b[2])), _f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def _f6_mul_v(a):
    # v * (c0 + c1 v + c2 v^2) = xi c2 + c0 v + c1 v^2
    return (_f2_mul_xi(a[2]), a[0], a[1])


def _f6_inv(a):
    c0 = _f2_sub(_f2_sqr(a[0]), _f2_mul_xi(_f2_mul(a[1], a[2])))
    c1 = _f2_sub(_f2_mul_xi(_f2_sqr(a[2])), _f2_mul(a[0], a[1]))
    c2 = _f2_sub(_f2_sqr(a[1]), _f2_mul(a[0], a[2]))
    t = _f2_add(
        _f2_mul_xi(_f2_add(_f2_mul(a[2], c1), _f2_mul(a[1], c2))), _f2_mul(a[0], c0)
    )
    tinv = _f2_inv(t)
    return (_f2_mul(c0, tinv), _f2_mul(c1, tinv), _f2_mul(c2, tinv))


_F6_ZERO = (_F2_ZERO, _F2_ZERO, _F2_ZERO)
_F6_ONE = (_F2_ONE, _F2_ZERO, _F2_ZERO)


# --- Fp12 = Fp6[w]/(w^2 - v): (c0, c1) --------------------------------------


def _f12_add(a, b):
    return (_f6_add(a[0], b[0]), _f6_add(a[1], b[1]))


def _f12_sub(a, b):
    return (_f6_sub(a[0], b[0]), _f6_sub(a[1], b[1]))


def _f12_mul(a, b):
    t0 = _f6_mul(a[0], b[0])
    t1 = _f6_mul(a[1], b[1])
    c0 = _f6_add(t0, _f6_mul_v(t1))
    c1 = _f6_sub(
        _f6_mul(_f6_add(a[0], a[1]), _f6_add(b[0], b[1])), _f6_add(t0, t1)
    )
    return (c0, c1)


def _f12_sqr(a):
    return _f12_mul(a, a)


def _f12_inv(a):
    t = _f6_inv(_f6_sub(_f6_mul(a[0], a[0]), _f6_mul_v(_f6_mul(a[1], a[1]))))
    return (_f6_mul(a[0], t), _f6_neg(_f6_mul(a[1], t)))


def _f12_pow(a, e: int):
    out = _F12_ONE
    base = a
    while e:
        if e & 1:
            out = _f12_mul(out, base)
        base = _f12_sqr(base)
        e >>= 1
    return out


_F12_ZERO = (_F6_ZERO, _F6_ZERO)
_F12_ONE = (_F6_ONE, _F6_ZERO)


def _fp_to_f12(x: int):
    return (((x % _P, 0), _F2_ZERO, _F2_ZERO), _F6_ZERO)


def _f2_to_f12(x):
    return ((x, _F2_ZERO, _F2_ZERO), _F6_ZERO)


# w = (0, 1) in Fp12-over-Fp6; w^2 = v
_W = (_F6_ZERO, _F6_ONE)
_W2 = (( _F2_ZERO, _F2_ONE, _F2_ZERO), _F6_ZERO)  # v
_W3 = (_F6_ZERO, (_F2_ZERO, _F2_ONE, _F2_ZERO))  # v w
_W2_INV = _f12_inv(_W2)
_W3_INV = _f12_inv(_W3)


# --- curve arithmetic (generic affine over any of the fields) ---------------


class _Ops:
    """Field operation bundle so one affine point implementation serves
    Fp (G1), Fp2 (G2 twist) and Fp12 (pairing) points."""

    def __init__(self, add, sub, neg, mul, sqr, inv, zero, one, scalar):
        self.add, self.sub, self.neg = add, sub, neg
        self.mul, self.sqr, self.inv = mul, sqr, inv
        self.zero, self.one, self.scalar = zero, one, scalar


_OPS1 = _Ops(
    lambda a, b: (a + b) % _P,
    lambda a, b: (a - b) % _P,
    lambda a: (-a) % _P,
    lambda a, b: (a * b) % _P,
    lambda a: (a * a) % _P,
    _inv,
    0,
    1,
    lambda a, k: (a * k) % _P,
)
_OPS2 = _Ops(_f2_add, _f2_sub, _f2_neg, _f2_mul, _f2_sqr, _f2_inv, _F2_ZERO, _F2_ONE, _f2_scalar)
_OPS12 = _Ops(
    _f12_add,
    _f12_sub,
    lambda a: (_f6_neg(a[0]), _f6_neg(a[1])),
    _f12_mul,
    _f12_sqr,
    _f12_inv,
    _F12_ZERO,
    _F12_ONE,
    lambda a, k: _f12_mul(a, _fp_to_f12(k)),
)

# points are (x, y) tuples or None for infinity


def _pt_double(ops: _Ops, pt):
    if pt is None:
        return None
    x, y = pt
    if y == ops.zero:
        return None
    lam = ops.mul(ops.scalar(ops.sqr(x), 3), ops.inv(ops.scalar(y, 2)))
    x3 = ops.sub(ops.sqr(lam), ops.scalar(x, 2))
    y3 = ops.sub(ops.mul(lam, ops.sub(x, x3)), y)
    return (x3, y3)


def _pt_add(ops: _Ops, p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if y1 == y2:
            return _pt_double(ops, p)
        return None
    lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sqr(lam), x1), x2)
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _pt_neg(ops: _Ops, p):
    return None if p is None else (p[0], ops.neg(p[1]))


def _pt_mul(ops: _Ops, p, k: int):
    if k < 0:
        return _pt_mul(ops, _pt_neg(ops, p), -k)
    out = None
    add = p
    while k:
        if k & 1:
            out = _pt_add(ops, out, add)
        add = _pt_double(ops, add)
        k >>= 1
    return out


def _on_g1(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x * x + _B)) % _P == 0


_B2 = _f2_scalar(_XI, _B)  # twist constant: 4(u+1)


def _on_g2_twist(p) -> bool:
    if p is None:
        return True
    x, y = p
    return _f2_sub(_f2_sqr(y), _f2_add(_f2_mul(_f2_sqr(x), x), _B2)) == _F2_ZERO


# --- derived G2 cofactor ----------------------------------------------------


def _derive_h2() -> int:
    """G2 cofactor from first principles (see module docstring): compute
    the two sextic-twist orders from the Frobenius trace and pick the one
    divisible by r; sanity-checked at import by the subgroup tests below."""
    n1 = _H1 * CURVE_ORDER
    t1 = _P + 1 - n1
    t2 = t1 * t1 - 2 * _P  # trace over Fp2
    # CM: t2^2 - 4 p^2 = -3 f^2
    f2 = (4 * _P * _P - t2 * t2) // 3
    f = _isqrt(f2)
    assert f * f == f2, "CM discriminant not a perfect square"
    for n in (
        _P * _P + 1 - (t2 + 3 * f) // 2,
        _P * _P + 1 - (t2 - 3 * f) // 2,
    ):
        if n % CURVE_ORDER == 0:
            return n // CURVE_ORDER
    raise AssertionError("no sextic twist order divisible by r")


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


_H2 = _derive_h2()


# --- pairing ----------------------------------------------------------------


def _untwist(q):
    """E'(Fp2) → E(Fp12): (x', y') ↦ (x'·w⁻², y'·w⁻³)."""
    if q is None:
        return None
    return (_f12_mul(_f2_to_f12(q[0]), _W2_INV), _f12_mul(_f2_to_f12(q[1]), _W3_INV))


def _line(ops: _Ops, p1, p2, at):
    """Evaluate the line through p1, p2 (or the tangent when equal) at
    ``at`` — all in E(Fp12)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = at
    if x1 != x2:
        lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    elif y1 == y2:
        lam = ops.mul(ops.scalar(ops.sqr(x1), 3), ops.inv(ops.scalar(y1, 2)))
    else:  # vertical
        return ops.sub(xt, x1)
    return ops.sub(ops.sub(yt, y1), ops.mul(lam, ops.sub(xt, x1)))


_FINAL_EXP = (_P**12 - 1) // CURVE_ORDER


def pairing(p_g1, q_g2):
    """Bilinear map G1 × G2 → Fp12 (inverse of the canonical optimal-ate —
    see module docstring; equality comparisons are unaffected).

    ``p_g1``: affine point on E(Fp) in the r-torsion; ``q_g2``: affine
    point on the twist E'(Fp2) in the r-torsion. Returns an Fp12 element.
    """
    if p_g1 is None or q_g2 is None:
        return _F12_ONE
    ops = _OPS12
    p12 = (_fp_to_f12(p_g1[0]), _fp_to_f12(p_g1[1]))
    q12 = _untwist(q_g2)
    t = q12
    f = _F12_ONE
    for bit in bin(_BLS_X)[3:]:
        f = _f12_mul(_f12_sqr(f), _line(ops, t, t, p12))
        t = _pt_double(ops, t)
        if bit == "1":
            f = _f12_mul(f, _line(ops, t, q12, p12))
            t = _pt_add(ops, t, q12)
    return _f12_pow(f, _FINAL_EXP)


# --- point (de)compression (ZCash BLS12-381 format) -------------------------


def g1_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0] + [0] * 47)
    x, y = p
    flags = 0x80 | (0x20 if y > (_P - 1) // 2 else 0)
    raw = x.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g1_decompress(data: bytes):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= _P:
        raise ValueError("G1 x out of range")
    y2 = (x * x * x + _B) % _P
    y = pow(y2, (_P + 1) // 4, _P)
    if (y * y) % _P != y2:
        raise ValueError("G1 x is not on the curve")
    if bool(flags & 0x20) != (y > (_P - 1) // 2):
        y = (-y) % _P
    point = (x, y)
    if _pt_mul(_OPS1, point, CURVE_ORDER) is not None:
        raise ValueError("G1 point not in the r-torsion subgroup")
    return point


def g2_compress(q) -> bytes:
    if q is None:
        return bytes([0xC0] + [0] * 95)
    (x0, x1), y = q
    flags = 0x80 | (0x20 if _f2_is_larger(y) else 0)
    raw = x1.to_bytes(48, "big") + x0.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g2_decompress(data: bytes):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= _P or x1 >= _P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y2 = _f2_add(_f2_mul(_f2_sqr(x), x), _B2)
    y = _f2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x is not on the twist")
    if bool(flags & 0x20) != _f2_is_larger(y):
        y = _f2_neg(y)
    point = (x, y)
    if _pt_mul(_OPS2, point, CURVE_ORDER) is not None:
        raise ValueError("G2 point not in the r-torsion subgroup")
    return point


# --- hash to G2 --------------------------------------------------------------

DEFAULT_DST = b"IPC_PROOFS_F3_BLS12381G2_TRY_INC_V1"


def _expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        raise ValueError("DST too long")
    h = hashlib.sha256
    b_in_bytes = 32
    r_in_bytes = 64
    ell = (length + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("expand_message_xmd output too long")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(r_in_bytes)
    l_i_b = length.to_bytes(2, "big")
    b0 = h(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    out = b""
    b_prev = h(b0 + b"\x01" + dst_prime).digest()
    out += b_prev
    for i in range(2, ell + 1):
        b_prev = h(bytes(a ^ b for a, b in zip(b0, b_prev)) + bytes([i]) + dst_prime).digest()
        out += b_prev
    return out[:length]


def hash_to_g2(msg: bytes, dst: bytes = DEFAULT_DST):
    """Deterministic hash to the G2 subgroup (try-and-increment over
    expand_message_xmd output + cofactor clearing — see module docstring
    for the SSWU divergence note)."""
    for ctr in range(256):
        uniform = _expand_message_xmd(msg + bytes([ctr]), dst, 128)
        x0 = int.from_bytes(uniform[:64], "big") % _P
        x1 = int.from_bytes(uniform[64:], "big") % _P
        x = (x0, x1)
        y2 = _f2_add(_f2_mul(_f2_sqr(x), x), _B2)
        y = _f2_sqrt(y2)
        if y is None:
            continue
        # canonical sign choice from the counter-stable derivation
        if _f2_is_larger(y):
            y = _f2_neg(y)
        point = _pt_mul(_OPS2, (x, y), _H2)
        if point is not None:
            return point
    raise AssertionError("hash_to_g2 failed to find a curve point")


# --- the signature scheme ----------------------------------------------------


def g1_generator():
    return _G1


def g2_generator():
    return _G2


def sk_to_pk(sk: int):
    """Public key = sk·G1 (Filecoin orientation: 48-byte G1 pubkeys)."""
    if not 0 < sk < CURVE_ORDER:
        raise ValueError("secret key out of range")
    return _pt_mul(_OPS1, _G1, sk)


def sign(sk: int, msg: bytes, dst: bytes = DEFAULT_DST):
    """Signature = sk·H(msg) ∈ G2."""
    if not 0 < sk < CURVE_ORDER:
        raise ValueError("secret key out of range")
    return _pt_mul(_OPS2, hash_to_g2(msg, dst), sk)


def verify(pk, msg: bytes, sig, dst: bytes = DEFAULT_DST) -> bool:
    """e(pk, H(msg)) == e(G1, sig)."""
    if pk is None or sig is None:
        return False
    return pairing(pk, hash_to_g2(msg, dst)) == pairing(_G1, sig)


def aggregate_signatures(sigs: Sequence):
    out = None
    for s in sigs:
        out = _pt_add(_OPS2, out, s)
    return out


def aggregate_pubkeys(pks: Sequence):
    out = None
    for p in pks:
        out = _pt_add(_OPS1, out, p)
    return out


def verify_aggregate_same_message(
    pks: Sequence, msg: bytes, agg_sig, dst: bytes = DEFAULT_DST
) -> bool:
    """All of ``pks`` signed the SAME message (the F3 certificate case:
    every signer signs the gpbft decide payload).

    Identity (infinity) public keys are REJECTED, per BLS KeyValidate: an
    identity key contributes nothing to the aggregate, so accepting one
    would let its table power count toward quorum without a signature.

    SECURITY: same-message aggregation is sound ONLY against keys with a
    verified proof of possession (`pop_verify`) — without PoP, a rogue key
    pk_evil = t·G1 − Σ pk_honest lets one participant forge the whole
    aggregate. Callers at a trust boundary must validate PoPs (the F3
    certificate path does, mirroring the POP ciphersuite go-f3 uses)."""
    if not pks or agg_sig is None:
        return False
    if any(pk is None for pk in pks):
        return False
    agg_pk = aggregate_pubkeys(pks)
    if agg_pk is None:
        return False
    return pairing(agg_pk, hash_to_g2(msg, dst)) == pairing(_G1, agg_sig)


POP_DST = b"IPC_PROOFS_F3_BLS_POP_V1"


def pop_prove(sk: int) -> "tuple":
    """Proof of possession: sign one's own compressed public key under the
    dedicated PoP domain tag. Registering a PoP is what makes
    same-message aggregation rogue-key safe (an attacker cannot produce a
    PoP for pk_evil = t·G1 − Σ pk_honest without its discrete log)."""
    pk = sk_to_pk(sk)
    return sign(sk, g1_compress(pk), POP_DST)


def pop_verify(pk, pop) -> bool:
    """Check a proof of possession for ``pk``."""
    if pk is None or pop is None:
        return False
    return verify(pk, g1_compress(pk), pop, POP_DST)
