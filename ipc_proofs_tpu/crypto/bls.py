"""BLS12-381 aggregate signatures, pure Python.

Fills the reference's open trust boundary: `src/proofs/trust/mod.rs:58,72`
leaves F3 certificate signature verification as TODOs and `src/cert.rs:52-64`
is a placeholder. This module provides the minimum-BLS scheme go-f3 style
certificates need: G1 public keys (48-byte compressed), G2 signatures
(96-byte compressed), same-message aggregation (every signer signs the gpbft
payload), verified with two pairings.

Performance stance: certificate verification runs ONCE per proof bundle, so
this is deliberately straightforward big-int Python (a pairing is ~0.5 s)
rather than a native or vectorized path — the hot loops of this framework
are elsewhere.

Implementation notes (round 5 closed the two interop divergences here —
the pairing is now the canonical optimal ate, and hash-to-G2 is RFC 9380
SSWU; NOTES_r05.md records the offline verification):

* Field tower: Fp2 = Fp[u]/(u²+1), Fp6 = Fp2[v]/(v³-ξ) with ξ = u+1,
  Fp12 = Fp6[w]/(w²-v). CANONICAL optimal-ate: Miller loop over |x| (the
  BLS parameter 0xd201000000010000) with affine line functions, the
  negative-x conjugation of the Miller value, and final exponentiation by
  the integer (p¹²-1)/r.
* Hash-to-G2 is RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_: hash_to_field
  (expand_message_xmd/SHA-256, L=64, m=2, count=2), simplified SWU on the
  3-isogenous curve E2', the 3-isogeny back to E2 (constants vendored from
  RFC 9380 App. E.3), and Budroni–Pintore cofactor clearing through the ψ
  endomorphism (whose constants are DERIVED at import, not vendored).
  Offline verification (tests/test_bls_sswu.py): SSWU outputs satisfy
  E2', the isogeny maps onto E2 and is a group homomorphism whose kernel
  x-coordinates are 3-division-polynomial roots of E2', outputs are
  r-torsion, and the ψ-clearing equals the spec's h_eff scalar multiple —
  two independently-derived clearings agreeing. Byte-level RFC vectors
  remain unfetchable in this zero-egress environment; these checks pin
  the construction up to the RFC's kernel choice.
* DSTs default to the BLS POP ciphersuite strings
  (``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_`` /
  ``BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_``) — the suite go-f3's
  blssig verifier uses.
* The G2 cofactor is derived at import from p, r and the G1 cofactor via
  the CM/twist order relations and checked (twist order divisible by r,
  cleared points r-torsion) rather than hard-coded.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

__all__ = [
    "PRIME",
    "CURVE_ORDER",
    "g1_generator",
    "g2_generator",
    "sk_to_pk",
    "sign",
    "verify",
    "aggregate_signatures",
    "aggregate_pubkeys",
    "verify_aggregate_same_message",
    "pop_prove",
    "pop_verify",
    "g1_compress",
    "g1_decompress",
    "g2_compress",
    "g2_decompress",
    "hash_to_g2",
    "pairing",
]

# --- parameters --------------------------------------------------------------

PRIME = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
CURVE_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
_BLS_X = 0xD201000000010000  # |x|; x itself is negative
_H1 = 0x396C8C005555E1568C00AAAB0000AAAB  # G1 cofactor

_P = PRIME
_B = 4  # E: y^2 = x^3 + 4

_G1 = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
_G2 = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# --- Fp ---------------------------------------------------------------------


def _inv(a: int) -> int:
    return pow(a, _P - 2, _P)


# --- Fp2 = Fp[u]/(u^2+1): (c0, c1) ------------------------------------------


def _f2_add(a, b):
    return ((a[0] + b[0]) % _P, (a[1] + b[1]) % _P)


def _f2_sub(a, b):
    return ((a[0] - b[0]) % _P, (a[1] - b[1]) % _P)


def _f2_neg(a):
    return ((-a[0]) % _P, (-a[1]) % _P)


def _f2_mul(a, b):
    a0b0 = a[0] * b[0]
    a1b1 = a[1] * b[1]
    return ((a0b0 - a1b1) % _P, ((a[0] + a[1]) * (b[0] + b[1]) - a0b0 - a1b1) % _P)


def _f2_sqr(a):
    return _f2_mul(a, a)


def _f2_scalar(a, k: int):
    return ((a[0] * k) % _P, (a[1] * k) % _P)


def _f2_inv(a):
    norm = (a[0] * a[0] + a[1] * a[1]) % _P
    ninv = _inv(norm)
    return ((a[0] * ninv) % _P, ((-a[1]) * ninv) % _P)


_F2_ZERO = (0, 0)
_F2_ONE = (1, 0)
_XI = (1, 1)  # u + 1


def _f2_mul_xi(a):
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return ((a[0] - a[1]) % _P, (a[0] + a[1]) % _P)


def _f2_is_larger(y) -> bool:
    """Lexicographic 'larger y' predicate over Fp2 (c1 first, then c0) —
    the single source of the compressed-point sign convention for
    compress, decompress, and hash-to-curve."""
    return y[1] > (_P - 1) // 2 or (y[1] == 0 and y[0] > (_P - 1) // 2)


def _f2_sqrt(a):
    """Square root in Fp2 by the complex method (p ≡ 3 mod 4); None if
    ``a`` is not a square."""
    c0, c1 = a
    if c1 == 0:
        s = pow(c0, (_P + 1) // 4, _P)
        if s * s % _P == c0:
            return (s, 0)
        # c0 is a non-residue: sqrt is purely imaginary, (t u)^2 = -t^2
        t = pow((-c0) % _P, (_P + 1) // 4, _P)
        if (t * t) % _P == (-c0) % _P:
            return (0, t)
        return None
    norm = (c0 * c0 + c1 * c1) % _P
    s = pow(norm, (_P + 1) // 4, _P)
    if (s * s) % _P != norm:
        return None
    inv2 = _inv(2)
    for sign in (s, (-s) % _P):
        re2 = (c0 + sign) * inv2 % _P
        re = pow(re2, (_P + 1) // 4, _P)
        if (re * re) % _P != re2 or re == 0:
            continue
        im = c1 * _inv(2 * re % _P) % _P
        cand = (re, im)
        if _f2_sqr(cand) == (c0 % _P, c1 % _P):
            return cand
    return None


# --- Fp6 = Fp2[v]/(v^3 - xi): (c0, c1, c2) ----------------------------------


def _f6_add(a, b):
    return (_f2_add(a[0], b[0]), _f2_add(a[1], b[1]), _f2_add(a[2], b[2]))


def _f6_sub(a, b):
    return (_f2_sub(a[0], b[0]), _f2_sub(a[1], b[1]), _f2_sub(a[2], b[2]))


def _f6_neg(a):
    return (_f2_neg(a[0]), _f2_neg(a[1]), _f2_neg(a[2]))


def _f6_mul(a, b):
    t0 = _f2_mul(a[0], b[0])
    t1 = _f2_mul(a[1], b[1])
    t2 = _f2_mul(a[2], b[2])
    c0 = _f2_add(t0, _f2_mul_xi(_f2_sub(_f2_mul(_f2_add(a[1], a[2]), _f2_add(b[1], b[2])), _f2_add(t1, t2))))
    c1 = _f2_add(
        _f2_sub(_f2_mul(_f2_add(a[0], a[1]), _f2_add(b[0], b[1])), _f2_add(t0, t1)),
        _f2_mul_xi(t2),
    )
    c2 = _f2_add(_f2_sub(_f2_mul(_f2_add(a[0], a[2]), _f2_add(b[0], b[2])), _f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def _f6_mul_v(a):
    # v * (c0 + c1 v + c2 v^2) = xi c2 + c0 v + c1 v^2
    return (_f2_mul_xi(a[2]), a[0], a[1])


def _f6_inv(a):
    c0 = _f2_sub(_f2_sqr(a[0]), _f2_mul_xi(_f2_mul(a[1], a[2])))
    c1 = _f2_sub(_f2_mul_xi(_f2_sqr(a[2])), _f2_mul(a[0], a[1]))
    c2 = _f2_sub(_f2_sqr(a[1]), _f2_mul(a[0], a[2]))
    t = _f2_add(
        _f2_mul_xi(_f2_add(_f2_mul(a[2], c1), _f2_mul(a[1], c2))), _f2_mul(a[0], c0)
    )
    tinv = _f2_inv(t)
    return (_f2_mul(c0, tinv), _f2_mul(c1, tinv), _f2_mul(c2, tinv))


_F6_ZERO = (_F2_ZERO, _F2_ZERO, _F2_ZERO)
_F6_ONE = (_F2_ONE, _F2_ZERO, _F2_ZERO)


# --- Fp12 = Fp6[w]/(w^2 - v): (c0, c1) --------------------------------------


def _f12_add(a, b):
    return (_f6_add(a[0], b[0]), _f6_add(a[1], b[1]))


def _f12_sub(a, b):
    return (_f6_sub(a[0], b[0]), _f6_sub(a[1], b[1]))


def _f12_mul(a, b):
    t0 = _f6_mul(a[0], b[0])
    t1 = _f6_mul(a[1], b[1])
    c0 = _f6_add(t0, _f6_mul_v(t1))
    c1 = _f6_sub(
        _f6_mul(_f6_add(a[0], a[1]), _f6_add(b[0], b[1])), _f6_add(t0, t1)
    )
    return (c0, c1)


def _f12_sqr(a):
    return _f12_mul(a, a)


def _f12_inv(a):
    t = _f6_inv(_f6_sub(_f6_mul(a[0], a[0]), _f6_mul_v(_f6_mul(a[1], a[1]))))
    return (_f6_mul(a[0], t), _f6_neg(_f6_mul(a[1], t)))


def _f12_pow(a, e: int):
    out = _F12_ONE
    base = a
    while e:
        if e & 1:
            out = _f12_mul(out, base)
        base = _f12_sqr(base)
        e >>= 1
    return out


_F12_ZERO = (_F6_ZERO, _F6_ZERO)
_F12_ONE = (_F6_ONE, _F6_ZERO)


def _fp_to_f12(x: int):
    return (((x % _P, 0), _F2_ZERO, _F2_ZERO), _F6_ZERO)


def _f2_to_f12(x):
    return ((x, _F2_ZERO, _F2_ZERO), _F6_ZERO)


# w = (0, 1) in Fp12-over-Fp6; w^2 = v
_W = (_F6_ZERO, _F6_ONE)
_W2 = (( _F2_ZERO, _F2_ONE, _F2_ZERO), _F6_ZERO)  # v
_W3 = (_F6_ZERO, (_F2_ZERO, _F2_ONE, _F2_ZERO))  # v w
_W2_INV = _f12_inv(_W2)
_W3_INV = _f12_inv(_W3)


# --- curve arithmetic (generic affine over any of the fields) ---------------


class _Ops:
    """Field operation bundle so one affine point implementation serves
    Fp (G1), Fp2 (G2 twist) and Fp12 (pairing) points."""

    def __init__(self, add, sub, neg, mul, sqr, inv, zero, one, scalar):
        self.add, self.sub, self.neg = add, sub, neg
        self.mul, self.sqr, self.inv = mul, sqr, inv
        self.zero, self.one, self.scalar = zero, one, scalar


_OPS1 = _Ops(
    lambda a, b: (a + b) % _P,
    lambda a, b: (a - b) % _P,
    lambda a: (-a) % _P,
    lambda a, b: (a * b) % _P,
    lambda a: (a * a) % _P,
    _inv,
    0,
    1,
    lambda a, k: (a * k) % _P,
)
_OPS2 = _Ops(_f2_add, _f2_sub, _f2_neg, _f2_mul, _f2_sqr, _f2_inv, _F2_ZERO, _F2_ONE, _f2_scalar)
_OPS12 = _Ops(
    _f12_add,
    _f12_sub,
    lambda a: (_f6_neg(a[0]), _f6_neg(a[1])),
    _f12_mul,
    _f12_sqr,
    _f12_inv,
    _F12_ZERO,
    _F12_ONE,
    lambda a, k: _f12_mul(a, _fp_to_f12(k)),
)

# points are (x, y) tuples or None for infinity


def _pt_double(ops: _Ops, pt):
    if pt is None:
        return None
    x, y = pt
    if y == ops.zero:
        return None
    lam = ops.mul(ops.scalar(ops.sqr(x), 3), ops.inv(ops.scalar(y, 2)))
    x3 = ops.sub(ops.sqr(lam), ops.scalar(x, 2))
    y3 = ops.sub(ops.mul(lam, ops.sub(x, x3)), y)
    return (x3, y3)


def _pt_add(ops: _Ops, p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if y1 == y2:
            return _pt_double(ops, p)
        return None
    lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sqr(lam), x1), x2)
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _pt_neg(ops: _Ops, p):
    return None if p is None else (p[0], ops.neg(p[1]))


def _pt_mul(ops: _Ops, p, k: int):
    if k < 0:
        return _pt_mul(ops, _pt_neg(ops, p), -k)
    out = None
    add = p
    while k:
        if k & 1:
            out = _pt_add(ops, out, add)
        add = _pt_double(ops, add)
        k >>= 1
    return out


def _on_g1(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x * x + _B)) % _P == 0


_B2 = _f2_scalar(_XI, _B)  # twist constant: 4(u+1)


def _on_g2_twist(p) -> bool:
    if p is None:
        return True
    x, y = p
    return _f2_sub(_f2_sqr(y), _f2_add(_f2_mul(_f2_sqr(x), x), _B2)) == _F2_ZERO


# --- derived G2 cofactor ----------------------------------------------------


def _derive_h2() -> int:
    """G2 cofactor from first principles (see module docstring): compute
    the two sextic-twist orders from the Frobenius trace and pick the one
    divisible by r; sanity-checked at import by the subgroup tests below."""
    n1 = _H1 * CURVE_ORDER
    t1 = _P + 1 - n1
    t2 = t1 * t1 - 2 * _P  # trace over Fp2
    # CM: t2^2 - 4 p^2 = -3 f^2
    f2 = (4 * _P * _P - t2 * t2) // 3
    f = _isqrt(f2)
    assert f * f == f2, "CM discriminant not a perfect square"
    for n in (
        _P * _P + 1 - (t2 + 3 * f) // 2,
        _P * _P + 1 - (t2 - 3 * f) // 2,
    ):
        if n % CURVE_ORDER == 0:
            return n // CURVE_ORDER
    raise AssertionError("no sextic twist order divisible by r")


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


_H2 = _derive_h2()


# --- pairing ----------------------------------------------------------------


def _untwist(q):
    """E'(Fp2) → E(Fp12): (x', y') ↦ (x'·w⁻², y'·w⁻³)."""
    if q is None:
        return None
    return (_f12_mul(_f2_to_f12(q[0]), _W2_INV), _f12_mul(_f2_to_f12(q[1]), _W3_INV))


def _line(ops: _Ops, p1, p2, at):
    """Evaluate the line through p1, p2 (or the tangent when equal) at
    ``at`` — all in E(Fp12)."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = at
    if x1 != x2:
        lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    elif y1 == y2:
        lam = ops.mul(ops.scalar(ops.sqr(x1), 3), ops.inv(ops.scalar(y1, 2)))
    else:  # vertical
        return ops.sub(xt, x1)
    return ops.sub(ops.sub(yt, y1), ops.mul(lam, ops.sub(xt, x1)))


_FINAL_EXP = (_P**12 - 1) // CURVE_ORDER


def pairing(p_g1, q_g2):
    """The canonical optimal-ate bilinear map G1 × G2 → Fp12 (Miller loop
    over |x| with the negative-x conjugation, final exponentiation by the
    integer (p¹²-1)/r).

    ``p_g1``: affine point on E(Fp) in the r-torsion; ``q_g2``: affine
    point on the twist E'(Fp2) in the r-torsion. Returns an Fp12 element.
    """
    if p_g1 is None or q_g2 is None:
        return _F12_ONE
    ops = _OPS12
    p12 = (_fp_to_f12(p_g1[0]), _fp_to_f12(p_g1[1]))
    q12 = _untwist(q_g2)
    t = q12
    f = _F12_ONE
    for bit in bin(_BLS_X)[3:]:
        f = _f12_mul(_f12_sqr(f), _line(ops, t, t, p12))
        t = _pt_double(ops, t)
        if bit == "1":
            f = _f12_mul(f, _line(ops, t, q12, p12))
            t = _pt_add(ops, t, q12)
    # x is NEGATIVE: the canonical optimal ate conjugates the Miller value
    # (f_{x} = conj(f_{|x|}) up to vertical lines the final exponentiation
    # kills). conj = p⁶-Frobenius: (c0, c1) → (c0, -c1) over Fp6.
    f = (f[0], _f6_neg(f[1]))
    return _f12_pow(f, _FINAL_EXP)


# --- point (de)compression (ZCash BLS12-381 format) -------------------------


def g1_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0] + [0] * 47)
    x, y = p
    flags = 0x80 | (0x20 if y > (_P - 1) // 2 else 0)
    raw = x.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g1_decompress(data: bytes):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= _P:
        raise ValueError("G1 x out of range")
    y2 = (x * x * x + _B) % _P
    y = pow(y2, (_P + 1) // 4, _P)
    if (y * y) % _P != y2:
        raise ValueError("G1 x is not on the curve")
    if bool(flags & 0x20) != (y > (_P - 1) // 2):
        y = (-y) % _P
    point = (x, y)
    if _pt_mul(_OPS1, point, CURVE_ORDER) is not None:
        raise ValueError("G1 point not in the r-torsion subgroup")
    return point


def g2_compress(q) -> bytes:
    if q is None:
        return bytes([0xC0] + [0] * 95)
    (x0, x1), y = q
    flags = 0x80 | (0x20 if _f2_is_larger(y) else 0)
    raw = x1.to_bytes(48, "big") + x0.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g2_decompress(data: bytes):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= _P or x1 >= _P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y2 = _f2_add(_f2_mul(_f2_sqr(x), x), _B2)
    y = _f2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x is not on the twist")
    if bool(flags & 0x20) != _f2_is_larger(y):
        y = _f2_neg(y)
    point = (x, y)
    if _pt_mul(_OPS2, point, CURVE_ORDER) is not None:
        raise ValueError("G2 point not in the r-torsion subgroup")
    return point


# --- hash to G2 --------------------------------------------------------------

# The BLS proof-of-possession ciphersuite DSTs (RFC 9380 / draft-bls-sig) —
# the suite go-f3's blssig verifier uses, making signatures interoperable.
DEFAULT_DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def _expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        raise ValueError("DST too long")
    h = hashlib.sha256
    b_in_bytes = 32
    r_in_bytes = 64
    ell = (length + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("expand_message_xmd output too long")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(r_in_bytes)
    l_i_b = length.to_bytes(2, "big")
    b0 = h(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    out = b""
    b_prev = h(b0 + b"\x01" + dst_prime).digest()
    out += b_prev
    for i in range(2, ell + 1):
        b_prev = h(bytes(a ^ b for a, b in zip(b0, b_prev)) + bytes([i]) + dst_prime).digest()
        out += b_prev
    return out[:length]


# --- RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_ -------------------------------
#
# hash_to_field → simplified SWU on the 3-isogenous curve E2' → 3-isogeny
# back to E2 → Budroni–Pintore cofactor clearing via the ψ endomorphism.
# The SSWU/isogeny constants are vendored from RFC 9380 §8.8.2 / App. E.3;
# tests/test_bls_sswu.py re-derives their load-bearing properties offline
# (E2' is 3-isogenous to E2, the map is a homomorphism landing on E2, its
# kernel x-coordinate is a 3-division-polynomial root, outputs are
# r-torsion, and the ψ-based clearing equals the spec's h_eff scalar).

# E2': y² = x³ + A'x + B' over Fp2 — the SSWU target curve
_SSWU_A = (0, 240)
_SSWU_B = (1012, 1012)
_SSWU_Z = ((-2) % _P, (-1) % _P)  # Z = -(2 + I)

# 3-isogeny E2' → E2, x = x_num/x_den, y = y' · y_num/y_den (App. E.3)
_ISO3_X_NUM = (
    (
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    (
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    (
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    (
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
)
_ISO3_X_DEN = (  # x_den = x'² + k_(2,1)·x' + k_(2,0)
    (
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    (
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
)
_ISO3_Y_NUM = (
    (
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    (
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    (
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    (
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
)
_ISO3_Y_DEN = (  # y_den = x'³ + k_(4,2)·x'² + k_(4,1)·x' + k_(4,0)
    (
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    (
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    (
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
)


def _f2_is_square(a) -> bool:
    """Quadratic-residue test via the norm map: a ∈ Fp2 is a square iff
    N(a) = a·ā = c0²+c1² is a square in Fp (N(a)^((p-1)/2) = a^((p²-1)/2))."""
    if a == _F2_ZERO:
        return True
    norm = (a[0] * a[0] + a[1] * a[1]) % _P
    return pow(norm, (_P - 1) // 2, _P) == 1


def _f2_sgn0(a) -> int:
    """RFC 9380 §4.1 sgn0 for Fp2 (m=2)."""
    sign_0 = a[0] & 1
    zero_0 = a[0] == 0
    return sign_0 | (zero_0 & (a[1] & 1))


_SSWU_NEG_B_OVER_A = None  # (-B/A, B/(Z·A)) — computed on first map call


def _sswu_g2(u):
    """Simplified SWU map Fp2 → E2' (RFC 9380 §6.6.2). Output is on E2'
    (y² = x³ + A'x + B'), deterministic in u."""
    global _SSWU_NEG_B_OVER_A
    A, B, Z = _SSWU_A, _SSWU_B, _SSWU_Z
    if _SSWU_NEG_B_OVER_A is None:
        _SSWU_NEG_B_OVER_A = (
            _f2_mul(_f2_neg(B), _f2_inv(A)),
            _f2_mul(B, _f2_inv(_f2_mul(Z, A))),
        )
    u2 = _f2_sqr(u)
    zu2 = _f2_mul(Z, u2)
    tv1 = _f2_add(_f2_sqr(zu2), zu2)  # Z²u⁴ + Zu²
    if tv1 == _F2_ZERO:
        x1 = _SSWU_NEG_B_OVER_A[1]
    else:
        x1 = _f2_mul(_SSWU_NEG_B_OVER_A[0], _f2_add(_F2_ONE, _f2_inv(tv1)))
    gx1 = _f2_add(_f2_add(_f2_mul(_f2_sqr(x1), x1), _f2_mul(A, x1)), B)
    if _f2_is_square(gx1):
        x, y = x1, _f2_sqrt(gx1)
    else:
        x2 = _f2_mul(zu2, x1)
        gx2 = _f2_add(_f2_add(_f2_mul(_f2_sqr(x2), x2), _f2_mul(A, x2)), B)
        x, y = x2, _f2_sqrt(gx2)
    assert y is not None, "SSWU: no root on either candidate (unreachable)"
    if _f2_sgn0(u) != _f2_sgn0(y):
        y = _f2_neg(y)
    return x, y


def _iso3_eval(coeffs, x):
    acc = _F2_ZERO
    for k in reversed(coeffs):
        acc = _f2_add(_f2_mul(acc, x), k)
    return acc


def _iso3_map(p):
    """The 3-isogeny E2' → E2 (rational map from the vendored table).
    Denominator zeros map to the point at infinity (the isogeny kernel)."""
    x, y = p
    x_den = _iso3_eval((*_ISO3_X_DEN, _F2_ONE), x)
    y_den = _iso3_eval((*_ISO3_Y_DEN, _F2_ONE), x)
    if x_den == _F2_ZERO or y_den == _F2_ZERO:
        return None
    x_out = _f2_mul(_iso3_eval(_ISO3_X_NUM, x), _f2_inv(x_den))
    y_out = _f2_mul(_f2_mul(y, _iso3_eval(_ISO3_Y_NUM, x)), _f2_inv(y_den))
    return x_out, y_out


# ψ: the untwist-Frobenius-twist endomorphism of E2. Its two Fp2 constants
# are DERIVED at import (no vendored values): candidates are powers of
# 1/ξ, selected by requiring ψ to (a) map E2 to E2 and (b) act on G2 as
# multiplication by the Frobenius eigenvalue t-1 = x (checked on the
# generator). Used by the Budroni–Pintore cofactor clearing.
def _derive_psi_constants():
    exp_x = (_P - 1) // 3
    exp_y = (_P - 1) // 2
    xi = (1, 1)
    base_x = _f2_pow(xi, exp_x)
    base_y = _f2_pow(xi, exp_y)
    candidates_x = (base_x, _f2_inv(base_x))
    candidates_y = (base_y, _f2_inv(base_y), _f2_neg(base_y), _f2_neg(_f2_inv(base_y)))
    gen = _G2
    eigen = _pt_mul(_OPS2, gen, (-_BLS_X) % CURVE_ORDER)  # [x]gen, x negative
    for cx in candidates_x:
        for cy in candidates_y:
            q = (_f2_mul(cx, _f2_conj(gen[0])), _f2_mul(cy, _f2_conj(gen[1])))
            if not _on_g2_twist(q):
                continue
            if q == eigen:
                return cx, cy
    raise AssertionError("psi constant derivation failed")


def _f2_conj(a):
    return (a[0], (-a[1]) % _P)


def _f2_pow(a, e: int):
    out = _F2_ONE
    base = a
    while e:
        if e & 1:
            out = _f2_mul(out, base)
        base = _f2_sqr(base)
        e >>= 1
    return out


_PSI_CX, _PSI_CY = None, None  # derived lazily (first hash/clearing call)


def _psi(p):
    global _PSI_CX, _PSI_CY
    if _PSI_CX is None:
        _PSI_CX, _PSI_CY = _derive_psi_constants()
    if p is None:
        return None
    return (_f2_mul(_PSI_CX, _f2_conj(p[0])), _f2_mul(_PSI_CY, _f2_conj(p[1])))


def clear_cofactor_g2(p):
    """Budroni–Pintore fast cofactor clearing for G2 (RFC 9380 App. G.3):
    [h_eff]P computed as [x²-x-1]P + [x-1]ψ(P) + ψ²([2]P), x the (negative)
    BLS parameter. Output is in the r-torsion subgroup G2."""
    if p is None:
        return None
    big_x = _BLS_X  # |x|
    t1 = _pt_mul(_OPS2, p, big_x * big_x + big_x - 1)  # [x²-x-1]P (x<0)
    t2 = _pt_neg(_OPS2, _pt_mul(_OPS2, _psi(p), big_x + 1))  # [x-1]ψ(P)
    t3 = _psi(_psi(_pt_double(_OPS2, p)))  # ψ²([2]P)
    return _pt_add(_OPS2, _pt_add(_OPS2, t1, t2), t3)


def _hash_to_field_fp2(msg: bytes, dst: bytes, count: int):
    """RFC 9380 §5.2 hash_to_field for Fp2 (m=2, L=64)."""
    length = count * 2 * 64
    uniform = _expand_message_xmd(msg, dst, length)
    out = []
    for i in range(count):
        c0 = int.from_bytes(uniform[i * 128 : i * 128 + 64], "big") % _P
        c1 = int.from_bytes(uniform[i * 128 + 64 : i * 128 + 128], "big") % _P
        out.append((c0, c1))
    return out


def hash_to_g2(msg: bytes, dst: bytes = DEFAULT_DST):
    """RFC 9380 hash_to_curve for BLS12381G2_XMD:SHA-256_SSWU_RO_:
    two field elements → SSWU on E2' → 3-isogeny to E2 → add → clear
    cofactor. Deterministic; output in G2."""
    u0, u1 = _hash_to_field_fp2(msg, dst, 2)
    q0 = _iso3_map(_sswu_g2(u0))
    q1 = _iso3_map(_sswu_g2(u1))
    return clear_cofactor_g2(_pt_add(_OPS2, q0, q1))


# --- the signature scheme ----------------------------------------------------


def g1_generator():
    return _G1


def g2_generator():
    return _G2


def sk_to_pk(sk: int):
    """Public key = sk·G1 (Filecoin orientation: 48-byte G1 pubkeys)."""
    if not 0 < sk < CURVE_ORDER:
        raise ValueError("secret key out of range")
    return _pt_mul(_OPS1, _G1, sk)


def sign(sk: int, msg: bytes, dst: bytes = DEFAULT_DST):
    """Signature = sk·H(msg) ∈ G2."""
    if not 0 < sk < CURVE_ORDER:
        raise ValueError("secret key out of range")
    return _pt_mul(_OPS2, hash_to_g2(msg, dst), sk)


def verify(pk, msg: bytes, sig, dst: bytes = DEFAULT_DST) -> bool:
    """e(pk, H(msg)) == e(G1, sig)."""
    if pk is None or sig is None:
        return False
    return pairing(pk, hash_to_g2(msg, dst)) == pairing(_G1, sig)


def aggregate_signatures(sigs: Sequence):
    out = None
    for s in sigs:
        out = _pt_add(_OPS2, out, s)
    return out


def aggregate_pubkeys(pks: Sequence):
    out = None
    for p in pks:
        out = _pt_add(_OPS1, out, p)
    return out


def verify_aggregate_same_message(
    pks: Sequence, msg: bytes, agg_sig, dst: bytes = DEFAULT_DST
) -> bool:
    """All of ``pks`` signed the SAME message (the F3 certificate case:
    every signer signs the gpbft decide payload).

    Identity (infinity) public keys are REJECTED, per BLS KeyValidate: an
    identity key contributes nothing to the aggregate, so accepting one
    would let its table power count toward quorum without a signature.

    SECURITY: same-message aggregation is sound ONLY against keys with a
    verified proof of possession (`pop_verify`) — without PoP, a rogue key
    pk_evil = t·G1 − Σ pk_honest lets one participant forge the whole
    aggregate. Callers at a trust boundary must validate PoPs (the F3
    certificate path does, mirroring the POP ciphersuite go-f3 uses)."""
    if not pks or agg_sig is None:
        return False
    if any(pk is None for pk in pks):
        return False
    agg_pk = aggregate_pubkeys(pks)
    if agg_pk is None:
        return False
    return pairing(agg_pk, hash_to_g2(msg, dst)) == pairing(_G1, agg_sig)


# standard PoP DST of the BLS POP ciphersuite (go-f3 parity)
POP_DST = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def pop_prove(sk: int) -> "tuple":
    """Proof of possession: sign one's own compressed public key under the
    dedicated PoP domain tag. Registering a PoP is what makes
    same-message aggregation rogue-key safe (an attacker cannot produce a
    PoP for pk_evil = t·G1 − Σ pk_honest without its discrete log)."""
    pk = sk_to_pk(sk)
    return sign(sk, g1_compress(pk), POP_DST)


def pop_verify(pk, pop) -> bool:
    """Check a proof of possession for ``pk``."""
    if pk is None or pop is None:
        return False
    return verify(pk, g1_compress(pk), pop, POP_DST)
