"""Cryptographic primitives with no external dependencies.

`bls` implements BLS12-381 aggregate signatures for F3 finality-certificate
verification (reference gap: `src/proofs/trust/mod.rs:58,72` leaves
signature/quorum as TODOs; `src/cert.rs:52-64` is a placeholder).
"""

from ipc_proofs_tpu.crypto import bls  # noqa: F401
