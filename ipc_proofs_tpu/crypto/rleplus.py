"""Filecoin RLE+ bitfields (go-bitfield wire format).

go-f3 certificates carry their ``Signers`` set as an RLE+ bitfield over
power-table row indices (go-bitfield's serialization, the same format
Filecoin consensus uses for sector bitfields). This module implements the
format bidirectionally with the spec's strict minimality rules so a
bitfield round-trips to the unique canonical byte string.

Wire format (bits consumed LSB-first within each byte):

- 2-bit version, must be ``00``;
- 1 bit: the value of the first run (1 = the bitfield starts with set bits);
- a sequence of run lengths, values alternating, each encoded as one of
  - ``1``               — run of length 1,
  - ``01`` + 4 bits     — run of length 2..15 (LSB-first length bits),
  - ``00`` + LEB128     — run of length >= 16 (varint bytes, bits LSB-first);
- zero-bit padding to the byte boundary.

Strictness (the spec requires decoders to reject non-minimal encodings —
each bitfield has exactly one valid serialization):

- zero-length runs are invalid;
- a short block encoding length < 2, or a long block encoding length < 16,
  is non-minimal and rejected;
- LEB128 varints must be minimal (no redundant trailing zero group);
- padding bits after the final run must all be zero, and confined to the
  final byte;
- the empty bitfield is ``bytes([0])`` — the version header with no runs,
  go-bitfield's encoder output for zero runs; ``b""`` is rejected (as
  go-bitfield's decoder does — callers with an optional-bytes field decide
  for themselves what absence means).

The decoded form used across this package is a sorted list of set-bit
indices (power-table rows).
"""

from __future__ import annotations

from typing import Iterable, Sequence

__all__ = ["encode_rleplus", "decode_rleplus", "runs_to_indices", "indices_to_runs"]

# Ceiling on a decoded run length / total bit width: signers bitmaps index
# power-table rows (thousands at most); a crafted certificate must not be
# able to make the verifier materialize billions of indices. go-bitfield
# similarly caps decoded length (its RLE byte size is consensus-capped).
MAX_BITS_DEFAULT = 1 << 24


class _BitWriter:
    def __init__(self) -> None:
        self._out = bytearray()
        self._acc = 0
        self._nbits = 0

    def write(self, value: int, nbits: int) -> None:
        """Append ``nbits`` of ``value``, LSB-first into the stream."""
        self._acc |= (value & ((1 << nbits) - 1)) << self._nbits
        self._nbits += nbits
        while self._nbits >= 8:
            self._out.append(self._acc & 0xFF)
            self._acc >>= 8
            self._nbits -= 8

    def finish(self) -> bytes:
        if self._nbits:
            self._out.append(self._acc & 0xFF)
            self._acc = 0
            self._nbits = 0
        # strip trailing zero bytes? NO — padding lives inside the final
        # byte only; a full zero byte would be non-minimal output, and the
        # writer never produces one (runs always emit at least one 1-bit
        # per block except long-form varint bytes, whose last byte is
        # nonzero by LEB128 minimality)
        return bytes(self._out)


class _BitReader:
    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0  # absolute bit position
        self._total = len(data) * 8

    @property
    def bits_left(self) -> int:
        return self._total - self._pos

    def read(self, nbits: int) -> int:
        if nbits > self.bits_left:
            raise ValueError("RLE+ truncated inside a block")
        out = 0
        for i in range(nbits):
            byte = self._data[self._pos >> 3]
            out |= ((byte >> (self._pos & 7)) & 1) << i
            self._pos += 1
        return out

    def rest_is_padding(self) -> bool:
        """True iff every remaining bit is zero (legal end-of-stream)."""
        pos = self._pos
        if pos >> 3 >= len(self._data):
            return True
        # remaining bits of the current byte
        if self._data[pos >> 3] >> (pos & 7):
            return False
        return not any(self._data[(pos >> 3) + 1 :])


def indices_to_runs(indices: Sequence[int]) -> list[tuple[int, int]]:
    """Sorted, distinct set-bit indices -> alternating (value, length) runs
    starting at bit 0."""
    runs: list[tuple[int, int]] = []
    prev_end = 0
    run_start = None
    last = None
    for idx in indices:
        if idx < 0:
            raise ValueError("negative bit index")
        if last is not None and idx <= last:
            raise ValueError("indices must be strictly increasing")
        if run_start is None:
            run_start = idx
        elif idx != last + 1:
            if run_start > prev_end:
                runs.append((0, run_start - prev_end))
            runs.append((1, last + 1 - run_start))
            prev_end = last + 1
            run_start = idx
        last = idx
    if run_start is not None:
        if run_start > prev_end:
            runs.append((0, run_start - prev_end))
        runs.append((1, last + 1 - run_start))
    return runs


def runs_to_indices(runs: Iterable[tuple[int, int]], max_bits: int) -> list[int]:
    out: list[int] = []
    pos = 0
    for value, length in runs:
        if pos + length > max_bits:
            raise ValueError(f"RLE+ bitfield exceeds {max_bits} bits")
        if value:
            out.extend(range(pos, pos + length))
        pos += length
    return out


def _write_varint(writer: _BitWriter, value: int) -> None:
    while True:
        group = value & 0x7F
        value >>= 7
        writer.write(group | (0x80 if value else 0), 8)
        if not value:
            return


def _read_varint(reader: _BitReader) -> int:
    value = 0
    shift = 0
    last_group = 0
    while True:
        byte = reader.read(8)
        last_group = byte & 0x7F
        value |= last_group << shift
        if not byte & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("RLE+ varint too long")
    if shift and last_group == 0:
        raise ValueError("RLE+ varint not minimally encoded")
    return value


def encode_rleplus(indices: Sequence[int]) -> bytes:
    """Canonical RLE+ bytes for a set of bit indices (sorted, distinct)."""
    runs = indices_to_runs(indices)
    writer = _BitWriter()
    writer.write(0, 2)  # version 00
    writer.write(runs[0][0] if runs else 0, 1)  # first run's value
    if not runs:
        return writer.finish()  # bytes([0]): go-bitfield's empty bitfield
    for _, length in runs:
        if length == 1:
            writer.write(1, 1)
        elif length < 16:
            writer.write(0b10, 2)  # bits 0,1 read in stream order
            writer.write(length, 4)
        else:
            writer.write(0b00, 2)
            _write_varint(writer, length)
    return writer.finish()


def decode_rleplus(data: bytes, max_bits: int = MAX_BITS_DEFAULT) -> list[int]:
    """Decode RLE+ bytes to the sorted set-bit indices; strict-canonical
    (rejects every non-minimal encoding — see module docstring)."""
    if not data:
        raise ValueError("empty RLE+ byte string (the empty bitfield is b'\\x00')")
    reader = _BitReader(data)
    if reader.read(2) != 0:
        raise ValueError("unsupported RLE+ version")
    value = reader.read(1)
    runs: list[tuple[int, int]] = []
    total = 0
    while not reader.rest_is_padding():
        head = reader.read(1)
        if head == 1:
            length = 1
        elif reader.read(1) == 1:
            length = reader.read(4)
            if length < 2:
                raise ValueError(
                    "non-minimal RLE+: short block encoding a length < 2"
                )
        else:
            length = _read_varint(reader)
            if length < 16:
                raise ValueError(
                    "non-minimal RLE+: long block encoding a length < 16"
                )
        total += length
        if total > max_bits:
            raise ValueError(f"RLE+ bitfield exceeds {max_bits} bits")
        runs.append((value, length))
        value ^= 1
    if not runs:
        # a bare version header with no runs is the empty bitfield — but
        # only in its canonical form: first-bit 0, single byte
        if data != b"\x00":
            raise ValueError("non-minimal RLE+ empty bitfield")
        return []
    if runs[-1][0] == 0:
        # a trailing 0-run adds no set bits: encode(decode(x)) would differ
        raise ValueError("non-minimal RLE+: trailing zero run")
    if reader.bits_left >= 8:
        # whole zero bytes after the final run are non-minimal padding —
        # canonical padding is only the final byte's leftover bits
        raise ValueError("non-minimal RLE+: trailing zero bytes")
    return runs_to_indices(runs, max_bits)
