"""Append-only write-ahead journal: the durability primitive.

Record framing (all integers little-endian)::

    MAGIC   4 bytes   b"IPJ1"
    LEN     4 bytes   u32 payload length
    CRC     4 bytes   u32 crc32(payload)
    PAYLOAD LEN bytes UTF-8 canonical JSON

Every append is write → flush → ``os.fsync`` before the caller is told
the record is durable, so a committed record survives SIGKILL and power
loss (up to the filesystem's own guarantees). A crash mid-append leaves
a *torn tail*: fewer bytes on disk than one full frame. The reader
detects that (frame extends past EOF) and reports the byte offset of the
last good record so the caller can truncate and resume — a torn tail is
an expected artifact of crashing, not corruption. A CRC mismatch on a
*complete* frame, a bad magic, or undecodable JSON can only come from
bit corruption or interleaved writers and raises the typed
`JournalError` instead of ever yielding a silently wrong record.

Fail-soft (ENOSPC / EROFS mid-run): `JournalWriter.append` returns
``False`` instead of raising once the backing file stops accepting
writes — the writer permanently degrades to in-memory (a half-written
frame may sit at the tail; appending after it would corrupt mid-file),
counts ``jobs.journal_failures`` per unpersisted record, and warns once.
The job keeps its completed set in memory, so the run still finishes
with a correct bundle — it just can't resume.

Crash fault hook (used by ``tools/crashtest.py``): when
``IPC_JOURNAL_CRASH_AT=N`` is set, the writer SIGKILLs its own process
at its N-th append (0-based) — after the full frame is fsync'd
(chunk-boundary kill), or, with ``IPC_JOURNAL_CRASH_TORN=K``, after
only the first K bytes of the frame reach disk (torn mid-record write).
A real SIGKILL, not an exception: no destructor, no atexit, no flush
runs, exactly like an OOM kill or a preemption.
"""

from __future__ import annotations

import json
import os
import signal
import struct
import zlib
from typing import Any, Optional

from ipc_proofs_tpu.utils.log import get_logger

__all__ = [
    "JOURNAL_MAGIC",
    "FRAME_HEADER",
    "JournalError",
    "JournalWriter",
    "frame_record",
    "read_journal",
    "read_journal_entries",
    "read_record_at",
]

JOURNAL_MAGIC = b"IPJ1"
_HEADER = struct.Struct("<4sII")  # magic, payload length, crc32(payload)
# the framing contract, exported: the storex segment store reuses the same
# header layout (with its own magic) so one CRC/torn-tail discipline covers
# every append-only file in the tree
FRAME_HEADER = _HEADER

logger = get_logger(__name__)


class JournalError(ValueError):
    """Typed journal integrity failure: CRC mismatch on a complete frame,
    bad magic, undecodable payload, duplicate or out-of-range chunk
    records, or a manifest that doesn't match the request. Never raised
    for a torn tail — that's normal crash residue and is recovered."""


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(JOURNAL_MAGIC, len(payload), zlib.crc32(payload)) + payload


def encode_record(obj: Any) -> bytes:
    """Canonical (sorted-key, compact) JSON — byte-stable framing for a
    given record object, so replay → re-journal round-trips identically."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode("utf-8")


def frame_record(obj: Any) -> bytes:
    """One complete journal frame for ``obj`` — the exact bytes `append`
    would write. Exported for the compaction path, which rebuilds a
    journal offline and atomically swaps it in."""
    return _frame(encode_record(obj))


def read_journal_entries(path: str) -> "tuple[list[tuple[Any, int, int]], int, bool]":
    """Like `read_journal` but each entry carries its frame location:
    ``(record, offset, end)`` with ``offset`` the frame start and ``end``
    one past the payload — so callers (the serve result spill) can later
    re-read a single record with `read_record_at` instead of pinning
    every payload in memory."""
    with open(path, "rb") as fh:
        data = fh.read()
    entries: "list[tuple[Any, int, int]]" = []
    off = 0
    size = len(data)
    while off < size:
        if size - off < _HEADER.size:
            return entries, off, True  # torn header at the tail
        magic, length, crc = _HEADER.unpack_from(data, off)
        if magic != JOURNAL_MAGIC:
            raise JournalError(f"bad journal magic at offset {off}: {magic!r}")
        end = off + _HEADER.size + length
        if end > size:
            return entries, off, True  # torn payload at the tail
        payload = data[off + _HEADER.size : end]
        if zlib.crc32(payload) != crc:
            raise JournalError(
                f"journal record checksum mismatch at offset {off} "
                f"(record {len(entries)})"
            )
        try:
            entries.append((json.loads(payload), off, end))
        except ValueError as exc:
            raise JournalError(
                f"journal record at offset {off} is not valid JSON: {exc}"
            ) from exc
        off = end
    return entries, off, False


def read_journal(path: str) -> "tuple[list[Any], int, bool]":
    """Replay every record in ``path``.

    Returns ``(records, good_offset, torn_tail)``: ``good_offset`` is the
    byte offset just past the last complete, CRC-verified record;
    ``torn_tail`` is True when trailing bytes past it don't form a full
    frame (crash mid-append) — the caller truncates to ``good_offset``
    before appending again. Raises `JournalError` on anything that is
    not explainable by a torn sequential append: bad magic, CRC mismatch
    on a fully-present frame, or a payload that isn't valid JSON.
    """
    entries, good_offset, torn = read_journal_entries(path)
    return [rec for rec, _, _ in entries], good_offset, torn


def read_record_at(path: str, offset: int) -> Any:
    """Re-read ONE record whose frame starts at ``offset`` (as reported by
    `read_journal_entries`). Full integrity discipline applies: bad magic,
    CRC mismatch, a frame extending past EOF, or undecodable JSON all
    raise `JournalError` — a spilled result is either byte-verified or
    reported corrupt, never silently wrong."""
    with open(path, "rb") as fh:
        fh.seek(offset)
        header = fh.read(_HEADER.size)
        if len(header) < _HEADER.size:
            raise JournalError(f"record at offset {offset} extends past EOF")
        magic, length, crc = _HEADER.unpack(header)
        if magic != JOURNAL_MAGIC:
            raise JournalError(f"bad journal magic at offset {offset}: {magic!r}")
        payload = fh.read(length)
    if len(payload) < length:
        raise JournalError(f"record at offset {offset} extends past EOF")
    if zlib.crc32(payload) != crc:
        raise JournalError(f"journal record checksum mismatch at offset {offset}")
    try:
        return json.loads(payload)
    except ValueError as exc:
        raise JournalError(
            f"journal record at offset {offset} is not valid JSON: {exc}"
        ) from exc


class JournalWriter:
    """fsync-per-record appender with permanent fail-soft degrade.

    ``fsync=False`` drops the per-record fsync (write+flush only) for
    callers that explicitly trade durability for throughput — the bench
    measures both; the default is the durable contract.
    """

    def __init__(self, path: str, metrics=None, fsync: bool = True):
        self.path = path
        self._metrics = metrics
        self._fsync = fsync
        self._fh: Optional[Any] = open(path, "ab")
        self._records = 0  # appends attempted by THIS writer (crash-hook clock)
        self.degraded = False
        self._warned = False
        crash_at = os.environ.get("IPC_JOURNAL_CRASH_AT", "")
        self._crash_at = int(crash_at) if crash_at else None
        torn = os.environ.get("IPC_JOURNAL_CRASH_TORN", "")
        self._crash_torn = int(torn) if torn else None

    @property
    def journal_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def _crash(self, frame: bytes) -> None:
        """Fault hook: die by real signal mid-append (see module doc).

        ``IPC_JOURNAL_CRASH_SIGNAL=TERM`` swaps the SIGKILL for SIGTERM —
        the orchestrator-preemption flavor (k8s eviction, spot reclaim):
        still abrupt when nothing catches it, but deliverable to a process
        with a drain handler installed. The crashtest grid runs both."""
        if self._crash_torn is not None:
            # tear the frame: persist only the first K bytes (clamped so at
            # least one byte is missing — a full frame wouldn't be torn)
            k = max(0, min(self._crash_torn, len(frame) - 1))
            self._fh.write(frame[:k])
        else:
            self._fh.write(frame)  # boundary kill: record fully committed
        self._fh.flush()
        os.fsync(self._fh.fileno())
        sig = (
            signal.SIGTERM
            if os.environ.get("IPC_JOURNAL_CRASH_SIGNAL", "").upper() == "TERM"
            else signal.SIGKILL
        )
        os.kill(os.getpid(), sig)

    def append(self, obj: Any) -> bool:
        """Durably append one record; True iff it reached disk."""
        if self.degraded or self._fh is None:
            if self._metrics is not None:
                self._metrics.count("jobs.journal_failures")
            return False
        from ipc_proofs_tpu.obs.trace import span as _span

        with _span("journal.append") as sp:
            return self._append_framed(obj, sp)

    def _append_framed(self, obj: Any, sp) -> bool:
        frame = _frame(encode_record(obj))
        sp.set_attr("bytes", len(frame))
        if self._crash_at is not None and self._records == self._crash_at:
            self._crash(frame)
        self._records += 1
        try:
            self._fh.write(frame)
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
        except OSError as exc:
            # ENOSPC/EROFS/…: a partial frame may now sit at the tail, so
            # never write again (it would corrupt mid-file); the torn tail
            # is discarded by the next resume like any crash residue
            self.degraded = True
            if self._metrics is not None:
                self._metrics.count("jobs.journal_failures")
            if not self._warned:
                self._warned = True
                logger.warning(
                    "journal %s unwritable (%s) — degrading to in-memory; "
                    "this run completes but cannot resume", self.path, exc,
                )
            return False
        return True

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
