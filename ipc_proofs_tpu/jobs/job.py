"""Resumable range jobs on top of the write-ahead journal.

Job directory layout::

    <job_dir>/
      manifest.json   request identity: params digest, range digest,
                      n_pairs / n_chunks / chunk_size  (written once,
                      atomically; a resume against a different request
                      raises JournalError instead of resuming stale state)
      journal.bin     append-only chunk records (journal.py framing)

Record vocabulary (one JSON object per record):

    {"t": "chunk",   "chunk": i, "digest": d, "bundle": <bundle obj>,
                     "verify": <verdict or null>}
    {"t": "verdict", "chunk": i, "digest": d, "verify": <verdict>}

A ``chunk`` record is THE commit point: once fsync'd, chunk ``i`` is
never regenerated. ``verdict`` records attach a later verify result to
an already-committed chunk (the verify stage runs behind the record
stage in the pipelined driver). `resume_or_create` replays the journal,
truncates a torn tail, and seeds the completed-chunk map that the range
drivers consult to skip work.

Counters (documented in `utils.metrics.DURABILITY_COUNTERS`):
``jobs.chunks_replayed`` (records recovered on resume), ``jobs.resume_ms``
(replay wall time), ``jobs.commit_us`` (microseconds spent serializing +
fsync'ing commit records — the journal's attributable cost),
``jobs.journal_failures`` (records that failed to persist, fail-soft),
plus the ``jobs.journal_bytes`` gauge.
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import threading
import time
from typing import Any, Optional

from ipc_proofs_tpu.jobs.journal import (
    JournalError,
    JournalWriter,
    frame_record,
    read_journal,
)
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.threads import locked
from ipc_proofs_tpu.utils.lockdep import named_lock

__all__ = [
    "JOBS_MANIFEST_NAME",
    "JOBS_JOURNAL_NAME",
    "RangeJob",
    "job_manifest",
    "resume_or_create",
]

JOBS_MANIFEST_NAME = "manifest.json"
JOBS_JOURNAL_NAME = "journal.bin"

logger = get_logger(__name__)


def job_manifest(spec_repr: bytes, pairs, chunk_size: int) -> dict:
    """Build the request-identity manifest for one range job.

    ``params_digest`` covers the proof request (event spec, storage
    specs, chunk size — `proofs.range._request_spec_repr`); the range
    digest covers every tipset CID in order, so a job dir can never
    resume a DIFFERENT range or request (same contract as the per-chunk
    checkpoint digests, lifted to the whole job).
    """
    h = hashlib.sha256(spec_repr)
    for pair in pairs:
        for cid in pair.parent.cids:
            h.update(cid.to_bytes())
        for cid in pair.child.cids:
            h.update(cid.to_bytes())
    chunk_size = max(1, int(chunk_size))
    n = len(pairs)
    return {
        "format": 1,
        "params_digest": hashlib.sha256(spec_repr).hexdigest(),
        "range_digest": h.hexdigest(),
        "n_pairs": n,
        "chunk_size": chunk_size,
        "n_chunks": (n + chunk_size - 1) // chunk_size,
    }


class RangeJob:
    """One resumable range job: completed-chunk map + journal appender.

    Commit methods are thread-safe: the pipelined driver's record and
    verify stages run several workers, and `JournalWriter.append` is NOT
    safe to call concurrently (interleaved frames would tear the journal,
    and the ``IPC_JOURNAL_CRASH_AT`` record-count clock in the crash
    harness must tick one append at a time). One lock serializes every
    append together with its completed-map update, so a journal record
    and the in-memory map can never disagree mid-commit.
    """

    def __init__(
        self,
        job_dir: str,
        manifest: dict,
        completed: "dict[int, dict]",
        writer: JournalWriter,
        metrics=None,
        compact_threshold_bytes: "Optional[int]" = None,
    ):
        self.job_dir = job_dir
        self.manifest = manifest
        self._lock = named_lock("RangeJob._lock")
        self.completed = completed  # guarded-by: _lock
        self._writer = writer  # guarded-by: _lock
        self._metrics = metrics
        # auto-compaction trigger (None/0 = manual `compact()` only)
        self._compact_threshold = compact_threshold_bytes
        self.compactions = 0  # guarded-by: _lock
        self._last_compact_bytes = 0  # guarded-by: _lock

    # -- resume side -----------------------------------------------------

    def has_chunk(self, index: int) -> bool:
        with self._lock:
            return index in self.completed

    def bundle_obj(self, index: int, expect_digest: "str | None" = None) -> Any:
        """The committed bundle JSON object for chunk ``index``; verifies
        the stored per-chunk digest when the caller knows it — a mismatch
        means the journal belongs to different data and must never be
        spliced into this run's bundle."""
        with self._lock:
            rec = self.completed[index]
        if expect_digest is not None and rec.get("digest") != expect_digest:
            raise JournalError(
                f"journal chunk {index} digest {rec.get('digest')!r} != "
                f"expected {expect_digest!r} (job dir holds a different range)"
            )
        return rec["bundle"]

    # -- commit side -----------------------------------------------------

    def commit_chunk(self, index: int, digest: "str | None", bundle, verify=None) -> bool:
        """Durably record chunk ``index`` as complete (fail-soft)."""
        t0 = time.thread_time()
        w0 = time.perf_counter()
        rec = {
            "t": "chunk",
            "chunk": index,
            "digest": digest,
            "bundle": bundle.to_json_obj(),
            "verify": verify,
        }
        with self._lock:
            ok = self._writer.append(rec)  # ipclint: disable=lock-held-blocking (durability: appends serialize under the job lock)
            self.completed[index] = rec
            self._maybe_compact_locked()
            jb = self._writer.journal_bytes
        self._commit_done(t0, w0, jb)
        return ok

    def commit_verdict(self, index: int, digest: "str | None", verify) -> bool:
        """Attach a verify verdict to an already-committed chunk."""
        t0 = time.thread_time()
        w0 = time.perf_counter()
        with self._lock:
            ok = self._writer.append(  # ipclint: disable=lock-held-blocking (durability: appends serialize under the job lock)
                {"t": "verdict", "chunk": index, "digest": digest, "verify": verify}
            )
            if index in self.completed:
                self.completed[index]["verify"] = verify
            self._maybe_compact_locked()
            jb = self._writer.journal_bytes
        self._commit_done(t0, w0, jb)
        return ok

    # -- compaction ------------------------------------------------------

    def compact(self) -> bool:
        """Snapshot the committed prefix into a fresh journal and swap it
        in atomically, bounding replay time.

        The fresh journal holds ONE merged chunk record per completed
        chunk (verdicts already folded into their chunk record in
        `completed`), in chunk order — replaying it reconstructs exactly
        the current completed map, so a crash at ANY byte is safe:

        - before the `os.replace`: the original journal is untouched (the
          snapshot is built in a ``.compact`` sidecar, which a later open
          simply overwrites);
        - after the `os.replace`: the journal IS the snapshot and replays
          to the same state.

        Returns True when the swap happened; False when skipped (degraded
        writer, nothing committed) or failed fail-soft (OSError — the
        original journal keeps appending as before).
        """
        with self._lock:
            return self._compact_locked()

    @locked
    def _maybe_compact_locked(self) -> None:
        threshold = self._compact_threshold
        if not threshold:
            return
        size = self._writer.journal_bytes
        if size < threshold:
            return
        # require real growth since the last snapshot, or every commit past
        # the threshold would re-snapshot an already-compact journal
        if self._last_compact_bytes and size < int(1.5 * self._last_compact_bytes):
            return
        self._compact_locked()

    @locked
    def _compact_locked(self) -> bool:
        if self._writer.degraded or not self.completed:
            return False
        jpath = self._writer.path
        tmp = jpath + ".compact"
        snapshot = b"".join(
            frame_record(self.completed[index]) for index in sorted(self.completed)
        )
        crash_bytes = os.environ.get("IPC_COMPACT_CRASH_BYTES", "")
        try:
            with open(tmp, "wb") as fh:
                if crash_bytes:
                    # crash hook (tools/crashtest.py): persist only the first
                    # K bytes of the snapshot, then die by real SIGKILL — the
                    # swap never happened, the live journal must be untouched
                    k = max(0, min(int(crash_bytes), len(snapshot) - 1))
                    fh.write(snapshot[:k])
                    fh.flush()
                    os.fsync(fh.fileno())  # ipclint: disable=lock-held-blocking (compaction sidecar must be durable before the swap)
                    os.kill(os.getpid(), signal.SIGKILL)
                fh.write(snapshot)
                fh.flush()
                os.fsync(fh.fileno())  # ipclint: disable=lock-held-blocking (compaction sidecar must be durable before the swap)
        except OSError as exc:
            logger.warning(
                "journal compaction of %s failed pre-swap (%s) — continuing "
                "on the uncompacted journal", jpath, exc,
            )
            return False
        fsync = self._writer._fsync
        self._writer.close()
        try:
            os.replace(tmp, jpath)
        except OSError as exc:
            self._writer = JournalWriter(jpath, metrics=self._metrics, fsync=fsync)
            logger.warning(
                "journal compaction of %s failed at swap (%s) — continuing "
                "on the uncompacted journal", jpath, exc,
            )
            return False
        if os.environ.get("IPC_COMPACT_CRASH_POST", ""):
            # crash hook: die right after the atomic swap — the journal IS
            # the snapshot now and must replay to the same completed map
            os.kill(os.getpid(), signal.SIGKILL)
        self._writer = JournalWriter(jpath, metrics=self._metrics, fsync=fsync)
        self.compactions += 1
        self._last_compact_bytes = self._writer.journal_bytes
        metrics = self._metrics
        if metrics is not None:
            metrics.count("jobs.compactions")
            metrics.set_gauge("jobs.journal_bytes", self._last_compact_bytes)
        logger.info(
            "journal %s compacted: %d chunks, %d bytes", jpath,
            len(self.completed), self._last_compact_bytes,
        )
        return True

    def _commit_done(self, t0: float, w0: float, journal_bytes: int) -> None:
        # Two clocks on purpose. jobs.commit_us is thread CPU time:
        # commits run in the pipelined driver's record stage, where wall
        # time would also count GIL/IO waits spent productively scanning
        # the NEXT chunk — CPU time is the part a commit actually steals
        # from compute. jobs.chunk_journal_us is wall time: the fsync
        # latency a waiting request experiences, surfaced per-request as
        # `journal_ms` in the serve plane's Server-Timing breakdown.
        if self._metrics is not None:
            self._metrics.count(
                "jobs.commit_us", int((time.thread_time() - t0) * 1e6)
            )
            self._metrics.count(
                "jobs.chunk_journal_us", int((time.perf_counter() - w0) * 1e6)
            )
            self._metrics.set_gauge("jobs.journal_bytes", journal_bytes)

    @property
    def journal_bytes(self) -> int:
        with self._lock:
            return self._writer.journal_bytes

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._writer.degraded

    def close(self) -> None:
        with self._lock:
            self._writer.close()

    def __enter__(self) -> "RangeJob":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _write_manifest_atomic(path: str, manifest: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, sort_keys=True, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def resume_or_create(
    job_dir: str,
    manifest: dict,
    metrics=None,
    fsync: bool = True,
    compact_threshold_bytes: "Optional[int]" = None,
) -> RangeJob:
    """Open (resuming) or initialize a job directory.

    Fresh dir: writes ``manifest.json`` atomically and starts an empty
    journal. Existing dir: the on-disk manifest must equal ``manifest``
    (JournalError otherwise — a job dir is bound to exactly one request),
    then the journal replays: complete records seed the completed-chunk
    map, a torn tail is truncated away, duplicate or malformed chunk
    records raise `JournalError`. Replay cost surfaces as
    ``jobs.chunks_replayed`` / ``jobs.resume_ms``.

    ``compact_threshold_bytes`` arms auto-compaction: once the journal
    grows past it, commits snapshot the committed prefix and swap it in
    (`RangeJob.compact`). Defaults to the ``IPC_JOURNAL_COMPACT_BYTES``
    env var; unset/0 means manual compaction only.
    """
    if compact_threshold_bytes is None:
        raw = os.environ.get("IPC_JOURNAL_COMPACT_BYTES", "")
        if raw:
            try:
                compact_threshold_bytes = int(raw)
            except ValueError:
                logger.warning("ignoring non-integer IPC_JOURNAL_COMPACT_BYTES=%r", raw)
    t0 = time.perf_counter()
    os.makedirs(job_dir, exist_ok=True)
    mpath = os.path.join(job_dir, JOBS_MANIFEST_NAME)
    if os.path.exists(mpath):
        try:
            with open(mpath) as fh:
                on_disk = json.load(fh)
        except ValueError as exc:
            raise JournalError(f"unreadable job manifest {mpath}: {exc}") from exc
        if on_disk != manifest:
            diff = sorted(
                k
                for k in set(on_disk) | set(manifest)
                if on_disk.get(k) != manifest.get(k)
            )
            raise JournalError(
                f"job dir {job_dir} was created for a different request "
                f"(manifest mismatch on {diff}); use a fresh --job-dir"
            )
    else:
        _write_manifest_atomic(mpath, manifest)

    jpath = os.path.join(job_dir, JOBS_JOURNAL_NAME)
    completed: "dict[int, dict]" = {}
    n_replayed = 0
    if os.path.exists(jpath):
        records, good_offset, torn = read_journal(jpath)
        n_chunks = int(manifest.get("n_chunks", 0))
        for pos, rec in enumerate(records):
            if not isinstance(rec, dict) or not isinstance(rec.get("chunk"), int):
                raise JournalError(f"malformed journal record {pos} in {jpath}")
            index = rec["chunk"]
            if index < 0 or index >= n_chunks:
                raise JournalError(
                    f"journal record {pos} names chunk {index}, outside "
                    f"[0, {n_chunks}) for this job"
                )
            kind = rec.get("t")
            if kind == "chunk":
                if index in completed:
                    raise JournalError(
                        f"duplicate journal record for chunk {index} "
                        f"(record {pos}) — journal is corrupt"
                    )
                completed[index] = rec
                n_replayed += 1
            elif kind == "verdict":
                if index not in completed:
                    raise JournalError(
                        f"verdict record {pos} for chunk {index} precedes "
                        f"its chunk record"
                    )
                completed[index]["verify"] = rec.get("verify")
            else:
                raise JournalError(f"unknown journal record type {kind!r} ({pos})")
        if torn:
            # crash residue: drop the partial frame so appends restart on a
            # record boundary (the chunk it described was never committed)
            logger.warning(
                "journal %s has a torn tail record — truncating to %d bytes "
                "(%d committed chunks survive)", jpath, good_offset, n_replayed,
            )
            with open(jpath, "r+b") as fh:
                fh.truncate(good_offset)
                fh.flush()
                os.fsync(fh.fileno())
    writer = JournalWriter(jpath, metrics=metrics, fsync=fsync)
    if metrics is not None:
        if n_replayed:
            metrics.count("jobs.chunks_replayed", n_replayed)
        metrics.count("jobs.resume_ms", int((time.perf_counter() - t0) * 1000))
        metrics.set_gauge("jobs.journal_bytes", writer.journal_bytes)
    return RangeJob(
        job_dir, manifest, completed, writer, metrics=metrics,
        compact_threshold_bytes=compact_threshold_bytes,
    )
