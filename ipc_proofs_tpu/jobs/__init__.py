"""Crash-safe resumable jobs: chunk-granular write-ahead journaling.

A *job* is one long-running proof request (a range generation, a serve
admission queue) whose progress survives process death. The journal is
the durability primitive (`journal.py`: fsync'd, length-prefixed,
CRC-checksummed append-only records with torn-tail recovery); `job.py`
builds the range-job layer on top (manifest identity, completed-chunk
replay, `resume_or_create`).
"""

from ipc_proofs_tpu.jobs.journal import (
    JOURNAL_MAGIC,
    JournalError,
    JournalWriter,
    read_journal,
)
from ipc_proofs_tpu.jobs.job import (
    JOBS_JOURNAL_NAME,
    JOBS_MANIFEST_NAME,
    RangeJob,
    job_manifest,
    resume_or_create,
)

__all__ = [
    "JOURNAL_MAGIC",
    "JournalError",
    "JournalWriter",
    "read_journal",
    "JOBS_JOURNAL_NAME",
    "JOBS_MANIFEST_NAME",
    "RangeJob",
    "job_manifest",
    "resume_or_create",
]
