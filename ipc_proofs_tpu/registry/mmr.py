"""RFC 6962-style Merkle tree over registry record payloads.

The provenance log is a parent-hash DAG two ways at once: each record
carries the digest of its predecessor (a linear hash chain, verified on
open), and the record payloads also feed this tree so any client can
demand an O(log n) **inclusion proof** that a record sits at a given
position under a published root, plus a **consistency proof** that one
published root extends another without rewriting history.

Hashing follows the Certificate Transparency discipline exactly — leaf
and interior hashes live in domain-separated namespaces so a leaf can
never masquerade as a node (or vice versa):

    leaf     = SHA-256(0x00 || payload)
    interior = SHA-256(0x01 || left || right)
    MTH(D[n]) splits at k, the largest power of two < n

`MerkleLog` keeps the peak stack of the mountain range (one hash per set
bit of the size), so ``append`` is O(1) amortized and ``root`` is
O(log n) — the serve plane's per-response cost never grows with history.
Proof *generation* walks the retained leaf-hash list (O(n) compute,
O(log n) proof bytes), which is the audit path, not the serve path.

The verifiers (`verify_inclusion`, `verify_consistency`) are pure
functions of public data — a stateless client needs only the proof, the
two roots, and the tree sizes, never the log.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

__all__ = [
    "MerkleLog",
    "consistency_path",
    "inclusion_path",
    "leaf_hash",
    "merkle_root",
    "node_hash",
    "verify_consistency",
    "verify_inclusion",
]


def leaf_hash(payload: bytes) -> bytes:
    """Domain-separated leaf hash: SHA-256(0x00 || payload)."""
    return hashlib.sha256(b"\x00" + payload).digest()


def node_hash(left: bytes, right: bytes) -> bytes:
    """Domain-separated interior hash: SHA-256(0x01 || left || right)."""
    return hashlib.sha256(b"\x01" + left + right).digest()


def _split(n: int) -> int:
    """The largest power of two strictly below ``n`` (n >= 2)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def merkle_root(leaves: Sequence[bytes]) -> bytes:
    """MTH over already-hashed leaves; the empty tree hashes to
    SHA-256("") per RFC 6962."""
    n = len(leaves)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return leaves[0]
    k = _split(n)
    return node_hash(merkle_root(leaves[:k]), merkle_root(leaves[k:]))


def inclusion_path(leaves: Sequence[bytes], index: int) -> List[bytes]:
    """PATH(index, D): the sibling hashes proving ``leaves[index]`` is
    under ``merkle_root(leaves)``. Raises IndexError out of range."""
    n = len(leaves)
    if not 0 <= index < n:
        raise IndexError(f"leaf index {index} out of range [0, {n})")
    if n == 1:
        return []
    k = _split(n)
    if index < k:
        return inclusion_path(leaves[:k], index) + [merkle_root(leaves[k:])]
    return inclusion_path(leaves[k:], index - k) + [merkle_root(leaves[:k])]


def verify_inclusion(
    leaf: bytes, index: int, size: int, path: Sequence[bytes], root: bytes
) -> bool:
    """RFC 9162 §2.1.3.2: recompute the root from ``leaf`` (already
    leaf-hashed) at ``index`` in a ``size``-leaf tree via ``path``."""
    if index < 0 or size <= 0 or index >= size:
        return False
    fn, sn = index, size - 1
    r = leaf
    for p in path:
        if sn == 0:
            return False
        if fn & 1 or fn == sn:
            r = node_hash(p, r)
            if not fn & 1:
                while fn and not fn & 1:
                    fn >>= 1
                    sn >>= 1
        else:
            r = node_hash(r, p)
        fn >>= 1
        sn >>= 1
    return sn == 0 and r == root


def consistency_path(leaves: Sequence[bytes], old_size: int) -> List[bytes]:
    """PROOF(old_size, D): the hashes proving the first ``old_size``
    leaves of this tree are exactly the tree that published the old
    root. Empty when the trees are the same size."""
    n = len(leaves)
    if not 0 < old_size <= n:
        raise IndexError(f"old size {old_size} out of range (0, {n}]")
    if old_size == n:
        return []
    return _subproof(leaves, old_size, True)


def _subproof(leaves: Sequence[bytes], m: int, complete: bool) -> List[bytes]:
    n = len(leaves)
    if m == n:
        return [] if complete else [merkle_root(leaves)]
    k = _split(n)
    if m <= k:
        return _subproof(leaves[:k], m, complete) + [merkle_root(leaves[k:])]
    return _subproof(leaves[k:], m - k, False) + [merkle_root(leaves[:k])]


def verify_consistency(
    old_size: int,
    new_size: int,
    old_root: bytes,
    new_root: bytes,
    path: Sequence[bytes],
) -> bool:
    """RFC 9162 §2.1.4.2: check that the ``new_size`` tree under
    ``new_root`` is an append-only extension of the ``old_size`` tree
    under ``old_root``."""
    if old_size < 0 or old_size > new_size:
        return False
    if old_size == new_size:
        return not path and old_root == new_root
    if old_size == 0:
        # every tree extends the empty tree; nothing to cross-check
        return not path and old_root == hashlib.sha256(b"").digest()
    path = list(path)
    if old_size & (old_size - 1) == 0:
        # old tree is a complete (power-of-two) subtree: its root is a
        # node of the new tree and the proof omits it — restore it
        path = [old_root] + path
    if not path:
        return False
    fn, sn = old_size - 1, new_size - 1
    while fn & 1:
        fn >>= 1
        sn >>= 1
    fr = sr = path[0]
    for p in path[1:]:
        if sn == 0:
            return False
        if fn & 1 or fn == sn:
            fr = node_hash(p, fr)
            sr = node_hash(p, sr)
            if not fn & 1:
                while fn and not fn & 1:
                    fn >>= 1
                    sn >>= 1
        else:
            sr = node_hash(sr, p)
        fn >>= 1
        sn >>= 1
    return sn == 0 and fr == old_root and sr == new_root


class MerkleLog:
    """Append-only tree state: the full leaf-hash list (proof source)
    plus the mountain-range peak stack (O(1) amortized append, O(log n)
    root). NOT thread-safe — the owning registry serializes access."""

    def __init__(self, leaves: Sequence[bytes] = ()):
        self._leaves: List[bytes] = []
        self._peaks: List[tuple] = []  # (height, hash), left-to-right
        for h in leaves:
            self.append(h)

    def append(self, leaf: bytes) -> int:
        """Add one leaf hash; returns its index."""
        index = len(self._leaves)
        self._leaves.append(leaf)
        self._peaks.append((0, leaf))
        # merge equal-height peaks — amortized O(1), exactly the binary
        # carry chain of incrementing the size
        while (
            len(self._peaks) >= 2
            and self._peaks[-1][0] == self._peaks[-2][0]
        ):
            h, right = self._peaks.pop()
            _, left = self._peaks.pop()
            self._peaks.append((h + 1, node_hash(left, right)))
        return index

    @property
    def size(self) -> int:
        return len(self._leaves)

    @property
    def leaves(self) -> List[bytes]:
        return self._leaves

    def root(self) -> bytes:
        """Fold the peaks right-to-left — equals MTH over all leaves."""
        if not self._peaks:
            return hashlib.sha256(b"").digest()
        acc = self._peaks[-1][1]
        for _, peak in reversed(self._peaks[:-1]):
            acc = node_hash(peak, acc)
        return acc

    def inclusion_path(self, index: int) -> List[bytes]:
        return inclusion_path(self._leaves, index)

    def consistency_path(self, old_size: int) -> List[bytes]:
        return consistency_path(self._leaves, old_size)

    def root_at(self, size: int) -> bytes:
        """The root the log had when it held ``size`` leaves."""
        if not 0 <= size <= len(self._leaves):
            raise IndexError(f"size {size} out of range [0, {len(self._leaves)}]")
        return merkle_root(self._leaves[:size])
