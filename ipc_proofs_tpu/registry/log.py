"""Hash-linked registry log: IPJ1 framing discipline, IPR1 magic.

Record framing (all integers little-endian, the exported
``jobs.journal.FRAME_HEADER`` layout)::

    MAGIC   4 bytes   b"IPR1"
    LEN     4 bytes   u32 payload length
    CRC     4 bytes   u32 crc32(payload)
    PAYLOAD LEN bytes UTF-8 canonical JSON

On top of the per-frame CRC each payload carries ``prev`` — the SHA-256
of the *previous* record's payload bytes — so the log is a hash chain:
rewriting any historical record breaks every link after it. The reader
applies the journal's exact torn-tail discipline: a frame extending past
EOF is normal crash residue (truncate and resume), while a CRC mismatch
on a complete frame, a bad magic, undecodable JSON, or a broken prev
link can only be corruption or tampering and raises the typed
`RegistryError` — never a silently wrong record.

Crash fault hooks mirror the journal's (`tools/crashtest.py --registry`):
``IPC_REGISTRY_CRASH_AT=N`` SIGKILLs at the N-th append after the full
frame is fsync'd; ``IPC_REGISTRY_CRASH_TORN=K`` persists only the first
K bytes of that frame first. ``IPC_JOURNAL_CRASH_SIGNAL=TERM`` swaps in
SIGTERM, same as the journal.
"""

from __future__ import annotations

import hashlib
import os
import signal
import struct
import zlib
from typing import Any, List, Optional, Tuple

from ipc_proofs_tpu.jobs.journal import FRAME_HEADER, encode_record
from ipc_proofs_tpu.utils.log import get_logger

__all__ = [
    "REGISTRY_MAGIC",
    "RegistryError",
    "RegistryWriter",
    "frame_registry_record",
    "read_registry_frames",
    "record_digest",
    "verify_chain",
]

REGISTRY_MAGIC = b"IPR1"
_HEADER: struct.Struct = FRAME_HEADER

logger = get_logger(__name__)


class RegistryError(ValueError):
    """Typed registry integrity failure: CRC mismatch on a complete
    frame, bad magic, undecodable payload, or a prev-link that doesn't
    match the preceding record's digest. Never raised for a torn tail —
    that's normal crash residue and is truncated on open."""


def record_digest(payload: bytes) -> str:
    """The chain link: hex SHA-256 of one record's payload bytes."""
    return hashlib.sha256(payload).hexdigest()


def frame_registry_record(obj: Any) -> bytes:
    """One complete IPR1 frame for ``obj`` (canonical sorted-key JSON)."""
    payload = encode_record(obj)
    return _HEADER.pack(REGISTRY_MAGIC, len(payload), zlib.crc32(payload)) + payload


def read_registry_frames(
    path: str, offset: int = 0
) -> "Tuple[List[Tuple[Any, bytes, int]], int, bool]":
    """Scan complete frames from ``offset``; returns
    ``([(record, payload_bytes, frame_offset), ...], good_offset, torn)``.

    ``good_offset`` is one past the last complete CRC-verified frame;
    ``torn`` is True when trailing bytes past it don't form a full frame
    (crash mid-append — the caller truncates before appending again).
    A missing file reads as empty. Integrity failures raise the typed
    `RegistryError`; prev-link verification is the caller's job (it
    spans frames, and a sibling scan may start mid-chain).
    """
    import json

    try:
        with open(path, "rb") as fh:
            fh.seek(offset)
            data = fh.read()
    except FileNotFoundError:
        return [], offset, False
    entries: "List[Tuple[Any, bytes, int]]" = []
    off = 0
    size = len(data)
    while off < size:
        if size - off < _HEADER.size:
            return entries, offset + off, True  # torn header at the tail
        magic, length, crc = _HEADER.unpack_from(data, off)
        if magic != REGISTRY_MAGIC:
            raise RegistryError(
                f"bad registry magic at offset {offset + off}: {magic!r}"
            )
        end = off + _HEADER.size + length
        if end > size:
            return entries, offset + off, True  # torn payload at the tail
        payload = data[off + _HEADER.size : end]
        if zlib.crc32(payload) != crc:
            raise RegistryError(
                f"registry record checksum mismatch at offset {offset + off}"
            )
        try:
            entries.append((json.loads(payload), payload, offset + off))
        except ValueError as exc:
            raise RegistryError(
                f"registry record at offset {offset + off} is not valid "
                f"JSON: {exc}"
            ) from exc
        off = end
    return entries, offset + off, False


def verify_chain(
    entries: "List[Tuple[Any, bytes, int]]", prev: str = ""
) -> str:
    """Walk the prev-links across ``entries`` (as returned by
    `read_registry_frames`), starting from ``prev`` (empty = chain
    head). Returns the digest of the last payload — the new chain tip —
    or raises `RegistryError` at the first broken link."""
    for rec, payload, off in entries:
        got = rec.get("prev") if isinstance(rec, dict) else None
        if got != prev:
            raise RegistryError(
                f"registry chain broken at offset {off}: record links "
                f"prev={got!r}, expected {prev!r}"
            )
        prev = record_digest(payload)
    return prev


class RegistryWriter:
    """Append-only frame writer with permanent fail-soft degrade.

    ``fsync=False`` (the serve-path default) writes+flushes per record
    without the per-record fsync — registry appends ride the response
    seal and must cost well under 1% of serve wall; the OS page cache
    makes loss on power-cut bounded, and a torn tail is recovered like
    any crash residue. ``fsync=True`` restores the journal's durable
    contract for audit-critical deployments.
    """

    def __init__(self, path: str, metrics=None, fsync: bool = False):
        self.path = path
        self._metrics = metrics
        self._fsync = fsync
        self._fh: Optional[Any] = open(path, "ab")
        self._records = 0  # appends attempted by THIS writer (crash-hook clock)
        self.degraded = False
        self._warned = False
        crash_at = os.environ.get("IPC_REGISTRY_CRASH_AT", "")
        self._crash_at = int(crash_at) if crash_at else None
        torn = os.environ.get("IPC_REGISTRY_CRASH_TORN", "")
        self._crash_torn = int(torn) if torn else None

    @property
    def log_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def truncate(self, good_offset: int) -> None:
        """Drop crash residue past the last complete frame before the
        first append (exactly the journal's resume discipline)."""
        if self._fh is None:
            return
        self._fh.truncate(good_offset)
        self._fh.seek(good_offset)

    def _crash(self, frame: bytes) -> None:
        """Fault hook: die by real signal mid-append (see module doc)."""
        if self._crash_torn is not None:
            k = max(0, min(self._crash_torn, len(frame) - 1))
            self._fh.write(frame[:k])
        else:
            self._fh.write(frame)  # boundary kill: record fully committed
        self._fh.flush()
        os.fsync(self._fh.fileno())
        sig = (
            signal.SIGTERM
            if os.environ.get("IPC_JOURNAL_CRASH_SIGNAL", "").upper() == "TERM"
            else signal.SIGKILL
        )
        os.kill(os.getpid(), sig)

    def append_frame(self, frame: bytes) -> bool:
        """Append one pre-built frame; True iff it reached the file."""
        if self.degraded or self._fh is None:
            if self._metrics is not None:
                self._metrics.count("registry.append_failures")
            return False
        if self._crash_at is not None and self._records == self._crash_at:
            self._crash(frame)
        self._records += 1
        try:
            self._fh.write(frame)
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
        except OSError as exc:
            # ENOSPC/EROFS/…: a partial frame may now sit at the tail, so
            # never write again; serving continues — the registry degrades,
            # it never blocks a response
            self.degraded = True
            if self._metrics is not None:
                self._metrics.count("registry.append_failures")
            if not self._warned:
                self._warned = True
                logger.warning(
                    "registry log %s unwritable (%s) — degrading; serving "
                    "continues without new provenance records", self.path, exc,
                )
            return False
        return True

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
