"""Proof provenance plane: hash-linked audit registry + inclusion proofs.

See `ipc_proofs_tpu.registry.registry` for the full contract. The short
version: every served bundle seals one ``IPR1`` frame into an
append-only, content-addressed log; the log is simultaneously a linear
hash chain (tamper breaks every later link) and an RFC 6962 Merkle tree
(O(1) amortized append, O(log n) inclusion and consistency proofs);
and its records double as the fleet-wide delta base directory.
"""

from ipc_proofs_tpu.registry.log import (
    REGISTRY_MAGIC,
    RegistryError,
    RegistryWriter,
    frame_registry_record,
    read_registry_frames,
    record_digest,
    verify_chain,
)
from ipc_proofs_tpu.registry.mmr import (
    MerkleLog,
    consistency_path,
    inclusion_path,
    leaf_hash,
    merkle_root,
    node_hash,
    verify_consistency,
    verify_inclusion,
)
from ipc_proofs_tpu.registry.registry import ProvenanceRegistry

__all__ = [
    "MerkleLog",
    "ProvenanceRegistry",
    "REGISTRY_MAGIC",
    "RegistryError",
    "RegistryWriter",
    "consistency_path",
    "frame_registry_record",
    "inclusion_path",
    "leaf_hash",
    "merkle_root",
    "node_hash",
    "read_registry_frames",
    "record_digest",
    "verify_chain",
    "verify_consistency",
    "verify_inclusion",
]
