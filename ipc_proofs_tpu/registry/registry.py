"""Provenance registry: the append-only audit log + fleet base directory.

One `ProvenanceRegistry` per serving process. It owns exactly one log
file, ``reg-<owner>.log`` in a directory the whole fleet shares — the
same multi-writer layout as the shared segment tier: every writer has a
single-writer file, readers scan siblings. Each log is independently a
hash chain (every record links the digest of its predecessor) *and*
feeds an RFC 6962 Merkle tree, so the process can publish a checkpoint
root and answer inclusion / consistency proofs about everything it ever
served.

Two record kinds share the chain:

``serve``
    Sealed at response time for every bundle that left this process —
    bundle digest, trace id, tenant, pair/filter key, verdict summary,
    wall time, and the bundle's canonical CID set. The CID set is what
    turns the audit log into a **delta base directory**: any shard that
    knows a digest can recover the base's CID set from whichever shard
    served it, without having held the bundle itself.

``base``
    A subscriber-fleet ack: (fleet, filter key, subscriber, digest,
    cursor). Fed by the delivery log's ack path, these let any shard
    compute the newest base digest acked by *every* member of a fleet —
    the base a post-failover delta can safely build on.

Fail-soft is absolute: a write failure degrades the registry
(``registry.append_failures``, `/healthz` reports it) but the in-memory
head never advances on a failed write and serving continues
bit-identical. A torn tail on open is crash residue — truncated and
counted, exactly like the jobs journal. Anything else wrong with the
bytes raises the typed `RegistryError`.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ipc_proofs_tpu.registry.log import (
    RegistryError,
    RegistryWriter,
    frame_registry_record,
    read_registry_frames,
    record_digest,
)
from ipc_proofs_tpu.registry.mmr import MerkleLog, leaf_hash
from ipc_proofs_tpu.utils.lockdep import named_lock
from ipc_proofs_tpu.utils.log import get_logger
from ipc_proofs_tpu.utils.threads import locked

__all__ = ["ProvenanceRegistry"]

logger = get_logger(__name__)

_LOG_PREFIX = "reg-"
_LOG_SUFFIX = ".log"


def _log_name(owner: str) -> str:
    safe = "".join(c if (c.isalnum() or c in "-_.") else "_" for c in owner)
    return f"{_LOG_PREFIX}{safe}{_LOG_SUFFIX}"


class ProvenanceRegistry:
    """Thread-safe provenance log + fleet-wide base directory.

    ``owner`` names this process's log file; every other ``reg-*.log``
    in ``root`` is a sibling shard's chain, folded into the directory
    lazily (on a base-lookup miss) and incrementally (from the last
    verified offset). Sibling trouble is fail-soft:
    ``registry.fleet_refresh_errors`` counts it, lookups just miss.
    """

    def __init__(
        self,
        root: str,
        owner: str = "main",
        metrics=None,
        *,
        fsync: bool = False,
        record_cids: bool = True,
    ):
        self.root = root
        self.owner = owner
        self.record_cids = record_cids
        self._metrics = metrics
        os.makedirs(root, exist_ok=True)
        self.path = os.path.join(root, _log_name(owner))
        # lock-order: ProvenanceRegistry._lock is leaf — nothing else is
        # acquired while held (Metrics._lock is declared globally-last
        # and exempt)
        self._lock = named_lock("ProvenanceRegistry._lock")
        self._records: List[Dict[str, Any]] = []  # guarded-by: _lock
        self._mmr = MerkleLog()  # guarded-by: _lock
        self._tip = ""  # guarded-by: _lock — digest of last payload
        self._digest_seq: Dict[str, int] = {}  # guarded-by: _lock
        # fleet base directory (own records + verified sibling records):
        self._base_cids: Dict[str, frozenset] = {}  # guarded-by: _lock
        # (fleet, key) -> sub -> (cursor, digest)  [latest ack per member]
        self._acks: Dict[Tuple[str, str], Dict[str, Tuple[int, str]]] = {}  # guarded-by: _lock
        # (fleet, key) -> digest -> set of subs that ever acked it
        self._ack_sets: Dict[Tuple[str, str], Dict[str, set]] = {}  # guarded-by: _lock
        # (fleet, key) -> digest -> monotonic ingest order (newest wins)
        self._ack_order: Dict[Tuple[str, str], Dict[str, int]] = {}  # guarded-by: _lock
        self._order = 0  # guarded-by: _lock
        # sibling owner -> [verified offset, chain tip]
        self._siblings: Dict[str, List] = {}  # guarded-by: _lock

        entries, good, torn = read_registry_frames(self.path)
        prev = ""
        for rec, payload, off in entries:
            got = rec.get("prev") if isinstance(rec, dict) else None
            if got != prev:
                raise RegistryError(
                    f"registry chain broken at offset {off} in {self.path}: "
                    f"record links prev={got!r}, expected {prev!r}"
                )
            prev = record_digest(payload)
            self._ingest_locked(rec, payload)
        if torn:
            if metrics is not None:
                metrics.count("registry.torn_tails")
            logger.warning(
                "registry log %s: torn tail truncated at offset %d "
                "(crash residue)", self.path, good,
            )
        self._writer = RegistryWriter(self.path, metrics=metrics, fsync=fsync)
        self._writer.truncate(good)

    # -- ingest ------------------------------------------------------------

    @locked  # construction-time callers run before the registry is published
    def _ingest_locked(self, rec: Dict[str, Any], payload: bytes) -> None:
        """Fold one verified own-log record into chain + tree + directory.
        Caller holds _lock (or is the single-threaded constructor)."""
        seq = len(self._records)
        self._records.append(rec)
        self._mmr.append(leaf_hash(payload))
        self._tip = record_digest(payload)
        self._fold_directory_locked(rec)
        digest = rec.get("digest")
        if rec.get("kind") == "serve" and digest:
            self._digest_seq[digest] = seq

    @locked
    def _fold_directory_locked(self, rec: Dict[str, Any]) -> None:
        """Directory-only ingest — used for both own and sibling records."""
        kind = rec.get("kind")
        digest = rec.get("digest") or ""
        if kind == "serve":
            cids = rec.get("cids")
            if digest and isinstance(cids, list) and cids:
                try:
                    self._base_cids[digest] = frozenset(
                        bytes.fromhex(c) for c in cids
                    )
                except (TypeError, ValueError):
                    pass  # malformed CID list: directory miss, never a fault
        elif kind == "base":
            fleet = rec.get("fleet") or ""
            key = rec.get("key") or ""
            sub = rec.get("sub") or ""
            if not (digest and sub):
                return
            self._order += 1
            fk = (fleet, key)
            try:
                cursor = int(rec.get("cursor") or 0)
            except (TypeError, ValueError):
                cursor = 0
            latest = self._acks.setdefault(fk, {})
            have = latest.get(sub)
            if have is None or cursor >= have[0]:
                latest[sub] = (cursor, digest)
            self._ack_sets.setdefault(fk, {}).setdefault(digest, set()).add(sub)
            self._ack_order.setdefault(fk, {})[digest] = self._order

    # -- append ------------------------------------------------------------

    @locked
    def _append_locked(self, rec: Dict[str, Any]) -> Optional[int]:
        rec["prev"] = self._tip
        frame = frame_registry_record(rec)
        if not self._writer.append_frame(frame):  # ipclint: disable=lock-held-blocking (durability: the frame lands before the head advances)
            return None  # head does NOT advance on a failed write
        payload = frame[12:]
        seq = len(self._records)
        self._ingest_locked(rec, payload)
        if self._metrics is not None:
            self._metrics.count("registry.appends")
        return seq

    def append_served(
        self,
        digest: str,
        *,
        trace: str = "",
        tenant: str = "",
        key: str = "",
        verdict: str = "",
        cids: Optional[frozenset] = None,
        t: Optional[float] = None,
    ) -> Optional[int]:
        """Seal one served bundle into the chain; returns its sequence
        number, or None when the registry is degraded (fail-soft)."""
        rec: Dict[str, Any] = {
            "kind": "serve",
            "digest": digest,
            "trace": trace,
            "tenant": tenant,
            "key": key,
            "verdict": verdict,
            "t": round(time.time() if t is None else t, 3),
        }
        if self.record_cids and cids:
            rec["cids"] = sorted(c.hex() for c in cids)
        with self._lock:
            return self._append_locked(rec)

    def append_base_ack(
        self, fleet: str, key: str, sub: str, digest: str, cursor: int
    ) -> Optional[int]:
        """Record one subscriber's delta-base advance for the fleet.
        Idempotent per (sub, cursor, digest) — replaying acked state after
        a restart doesn't grow the chain."""
        rec = {
            "kind": "base",
            "fleet": fleet,
            "key": key,
            "sub": sub,
            "digest": digest,
            "cursor": int(cursor),
            "t": round(time.time(), 3),
        }
        with self._lock:
            have = self._acks.get((fleet, key), {}).get(sub)
            if have == (int(cursor), digest):
                return None  # already on the chain (restart replay)
            return self._append_locked(rec)

    # -- proofs ------------------------------------------------------------

    @property
    def degraded(self) -> bool:
        return self._writer.degraded

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def head(self) -> Dict[str, Any]:
        """The published checkpoint: owner, size, tree root, chain tip."""
        with self._lock:
            return {
                "owner": self.owner,
                "size": self._mmr.size,
                "root": self._mmr.root().hex(),
                "tip": self._tip,
                "log_bytes": self._writer.log_bytes,
                "degraded": self._writer.degraded,
            }

    def entry(self, seq: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not 0 <= seq < len(self._records):
                return None
            return dict(self._records[seq], seq=seq)

    def seq_of(self, digest: str) -> Optional[int]:
        """The sequence of the (latest) serve record for a bundle digest."""
        with self._lock:
            return self._digest_seq.get(digest)

    def inclusion_proof(self, seq: int) -> Optional[Dict[str, Any]]:
        """O(log n) proof that record ``seq`` is under the current root."""
        with self._lock:
            if not 0 <= seq < self._mmr.size:
                return None
            out = {
                "seq": seq,
                "size": self._mmr.size,
                "root": self._mmr.root().hex(),
                "leaf": self._mmr.leaves[seq].hex(),
                "path": [h.hex() for h in self._mmr.inclusion_path(seq)],
                "record": dict(self._records[seq]),
            }
        if self._metrics is not None:
            self._metrics.count("registry.proofs")
        return out

    def consistency(self, old_size: int) -> Optional[Dict[str, Any]]:
        """Proof that the current tree extends the ``old_size`` checkpoint."""
        with self._lock:
            if not 0 <= old_size <= self._mmr.size:
                return None
            out = {
                "old_size": old_size,
                "size": self._mmr.size,
                "old_root": self._mmr.root_at(old_size).hex(),
                "root": self._mmr.root().hex(),
                "path": [
                    h.hex()
                    for h in (
                        self._mmr.consistency_path(old_size) if old_size else []
                    )
                ],
            }
        if self._metrics is not None:
            self._metrics.count("registry.proofs")
        return out

    # -- fleet base directory ----------------------------------------------

    def lookup_base(self, digest: str) -> Optional[frozenset]:
        """CID set of a base digest, from ANY shard's serve records.
        A miss triggers one incremental sibling rescan before giving up."""
        with self._lock:
            cids = self._base_cids.get(digest)
            if cids is not None:
                return cids
            self._refresh_fleet_locked()
            return self._base_cids.get(digest)

    def fleet_acked_base(
        self, fleet: str, key: str, sub: str
    ) -> Optional[str]:
        """The base digest ``sub`` last acked under ``(fleet, key)`` — as
        recorded by WHICHEVER shard served it. A replacement shard with a
        fresh delivery log uses this instead of its (empty) local acked
        state, so subscriber deltas survive the shard that held them.

        Always rescans the sibling logs first (incremental — per-sibling
        offsets): unlike ``lookup_base`` (content-addressed, a hit can't
        be stale) an ack is latest-wins, and this shard's own records may
        predate the ack another shard sealed after taking the sub over."""
        with self._lock:
            self._refresh_fleet_locked()
            have = self._acks.get((fleet, key), {}).get(sub)
            return have[1] if have else None

    def newest_common_base(self, fleet: str, key: str) -> Optional[str]:
        """The newest digest acked by EVERY observed member of
        ``(fleet, key)`` — the base a post-failover delta can build on.
        None when the fleet has no common base (serve full)."""
        with self._lock:
            self._refresh_fleet_locked()
            fk = (fleet, key)
            latest = self._acks.get(fk)
            if not latest:
                return None
            members = set(latest)
            common = [
                d
                for d, subs in self._ack_sets.get(fk, {}).items()
                if members <= subs
            ]
            if not common:
                return None
            order = self._ack_order.get(fk, {})
            return max(common, key=lambda d: order.get(d, -1))

    def refresh_fleet(self) -> None:
        """Fold new sibling-log records into the base directory."""
        with self._lock:
            self._refresh_fleet_locked()

    @locked
    def _refresh_fleet_locked(self) -> None:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            if self._metrics is not None:
                self._metrics.count("registry.fleet_refresh_errors")
            return
        own = _log_name(self.owner)
        for name in names:
            if (
                not name.startswith(_LOG_PREFIX)
                or not name.endswith(_LOG_SUFFIX)
                or name == own
            ):
                continue
            state = self._siblings.setdefault(name, [0, ""])
            try:
                entries, good, _torn = read_registry_frames(
                    os.path.join(self.root, name), state[0]
                )
                prev = state[1]
                for rec, payload, off in entries:
                    got = rec.get("prev") if isinstance(rec, dict) else None
                    if got != prev:
                        raise RegistryError(
                            f"sibling chain broken at offset {off} in {name}"
                        )
                    prev = record_digest(payload)
                    self._fold_directory_locked(rec)
                state[0] = good
                state[1] = prev
            except (RegistryError, OSError) as exc:
                # a sibling's corruption must not take this shard down:
                # count it, stop ingesting that log, keep serving
                if self._metrics is not None:
                    self._metrics.count("registry.fleet_refresh_errors")
                logger.warning(
                    "registry sibling scan failed for %s: %s", name, exc
                )

    def close(self) -> None:
        self._writer.close()
